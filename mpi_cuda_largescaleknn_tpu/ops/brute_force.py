"""Exact blocked brute-force kNN update (the default query engine).

The reference's inner hot path is a per-thread stack-free kd-tree traversal
(``cukd::stackFree::knn`` called from ``runQuery``, unorderedDataVariant.cu:86).
On a GPU, one scalar thread per query makes a branchy tree walk cheap; on a
TPU the VPU/MXU want dense regular tiles, and for 3-component points an exact
blocked distance evaluation is the hardware-native formulation (cf. TPU-KNN,
arXiv:2206.14286). This module is that engine: for each (query-tile,
point-tile) pair compute the full f32 squared-distance tile and fold it into
the persistent candidate state.

Exactness: by default dist2 is computed elementwise on f32 operands (fixed
left-to-right component order — at D=3 the exact ``(dx*dx + dy*dy) + dz*dz``
tree) — the same value the reference's traversal computes per visited
point. ``score_dtype="bf16"`` switches to the ``|q|^2 + |p|^2 - 2 q.p``
MXU form (ops/distance.py): the cross term is one bf16 dot_general with
f32 accumulation, and because the expansion's cancellation error is
unbounded relative to the direct form, the approx scores only SELECT the
top ``rescore_width(k)`` survivors per row, which are rescored with the
exact elementwise f32 form before the merge — values entering the
candidate state are never approximate. At D=3 the MXU would run at K=3/128
utilization, so f32/VPU stays the default; the matmul form is the high-D
lever. (Selection itself is exact — no accumulation across pairs — but XLA
may contract ``a*b + c`` into FMA differently per fusion context, so
distances agree across *engines* to <= 1 ulp, not always bit-for-bit;
within one engine results are deterministic.)

The layout is D-generic throughout: points are ``f32[N, D]`` and the tile
reshapes derive D from the inputs (the PAD_SENTINEL padding path included).

A kd-tree traversal engine also exists (ops/traverse.py) and is benchmarked
against this one; sentinel-padded tiles cost O(N) per query here vs O(log N)
there, but with perfect vectorization and no divergence.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from mpi_cuda_largescaleknn_tpu.core.types import PAD_SENTINEL, CandidateState
from mpi_cuda_largescaleknn_tpu.ops.candidates import merge_candidates
from mpi_cuda_largescaleknn_tpu.ops.distance import (
    elementwise_dist2,
    score_tile,
    validate_score_dtype,
)
from mpi_cuda_largescaleknn_tpu.utils.math import cdiv


def pairwise_dist2(q: jnp.ndarray, p: jnp.ndarray) -> jnp.ndarray:
    """f32[Tq,D] x f32[Tp,D] -> f32[Tq,Tp] squared distances, fixed
    left-to-right component order (x,y,z at D=3)."""
    return elementwise_dist2(q, p)


def _pad_rows(arr, target, fill):
    n = arr.shape[0]
    if n == target:
        return arr
    pad_shape = (target - n,) + arr.shape[1:]
    return jnp.concatenate([arr, jnp.full(pad_shape, fill, arr.dtype)], axis=0)


def knn_update_bruteforce(state: CandidateState, queries: jnp.ndarray,
                          points: jnp.ndarray, point_ids: jnp.ndarray | None = None,
                          *, query_tile: int = 2048, point_tile: int = 2048,
                          score_dtype: str = "f32") -> CandidateState:
    """Fold every ``points`` row into each query's candidate state.

    Equivalent to one ``runQuery`` kernel launch of the reference
    (unorderedDataVariant.cu:199-203): queries and state stay put, ``points``
    is whatever tree shard is resident this round. Sentinel-padded rows in
    either input are harmless (their distances are +inf / their results are
    discarded by the caller). ``score_dtype="bf16"`` scores each [Tq, Tp]
    tile on the MXU with an exact f32 rescore of the survivors
    (ops/distance.py — the module docstring has the exactness argument).
    """
    validate_score_dtype(score_dtype)
    num_q, k = state.dist2.shape
    num_p = points.shape[0]
    dim = queries.shape[-1]
    if point_ids is None:
        point_ids = jnp.arange(num_p, dtype=jnp.int32)

    qt = min(query_tile, max(num_q, 1))
    pt = min(point_tile, max(num_p, 1))
    nq_tiles = cdiv(num_q, qt)
    np_tiles = cdiv(num_p, pt)

    # pad to whole tiles; sentinel queries produce garbage rows we slice off,
    # sentinel points produce +inf distances that never merge in
    q_pad = _pad_rows(jnp.asarray(queries, jnp.float32), nq_tiles * qt, PAD_SENTINEL)
    p_pad = _pad_rows(jnp.asarray(points, jnp.float32), np_tiles * pt, PAD_SENTINEL)
    id_pad = _pad_rows(jnp.asarray(point_ids, jnp.int32), np_tiles * pt, -1)
    d2_pad = _pad_rows(state.dist2, nq_tiles * qt, jnp.inf)
    idx_pad = _pad_rows(state.idx, nq_tiles * qt, -1)

    q_tiles = q_pad.reshape(nq_tiles, qt, dim)
    p_tiles = p_pad.reshape(np_tiles, pt, dim)
    id_tiles = id_pad.reshape(np_tiles, pt)
    d2_tiles = d2_pad.reshape(nq_tiles, qt, k)
    idx_tiles = idx_pad.reshape(nq_tiles, qt, k)

    def one_query_tile(args):
        q, hd2, hidx = args

        def step(carry, tile):
            st = CandidateState(*carry)
            p_t, id_t = tile
            d2, ids = score_tile(q, p_t, id_t, k, score_dtype=score_dtype)
            st = merge_candidates(st, d2, ids)
            return (st.dist2, st.idx), None

        (hd2, hidx), _ = jax.lax.scan(step, (hd2, hidx), (p_tiles, id_tiles))
        return hd2, hidx

    # sequential over query tiles (bounds live memory to one [qt, pt] tile);
    # each tile is qt*pt-wide data-parallel work, plenty for the VPU
    out_d2, out_idx = jax.lax.map(one_query_tile, (q_tiles, d2_tiles, idx_tiles))
    out_d2 = out_d2.reshape(nq_tiles * qt, k)[:num_q]
    out_idx = out_idx.reshape(nq_tiles * qt, k)[:num_q]
    return CandidateState(out_d2, out_idx)
