"""Bucketed nearest-first kNN engine — the TPU-native traversal.

This engine is to a TPU what ``cukd::stackFree::knn`` (the reference's inner
hot path, unorderedDataVariant.cu:86) is to a GPU. The GPU walks one implicit
tree node per scalar thread, pruning subtrees farther than the query's
current k-th candidate; a TPU has no scalar threads, so the same
prune-ordered traversal is lifted to *tile* granularity:

- points and queries are median-split into contiguous spatial buckets with
  tight AABBs (ops/partition.py) — the tree's top levels;
- every query bucket visits point buckets in ascending box-distance order
  (the GPU traversal's "close child first" rule, made global);
- a bucket is visited only while its squared box distance is strictly below
  the query bucket's current worst k-th-candidate distance — the identical
  prune predicate of the traversal (``cl.maxRadius2()``) and of the demand
  engine's ``computeMyPeer`` (box-dist >= cutoff skips,
  prePartitionedDataVariant.cu:157-174), so the search remains EXACT;
- the loop ends when every query bucket's next-nearest unvisited bucket is
  already beyond its radius — per-device early exit with no host round trip.

Within a visited bucket pair the work is a dense [S, T] score tile folded
into the persistent candidate rows — perfectly regular VPU work under the
default exact elementwise scorer, or MXU matmuls under
``score_dtype="bf16"`` (the ‖q‖²+‖p‖²−2q·p expansion with an exact f32
rescore of the survivors — ops/distance.py; final results bit-identical
whenever the true top-k sits inside the rescore window, which everything
short of engineered sub-bf16-ulp tie classes does — docs/TUNING.md
"Distance kernel" has the bound). For n uniform points this does
O(visited_buckets * S * T) ~ O(k + surface) distance evaluations per query
instead of brute force's O(n), while keeping every op a static-shape tile.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from mpi_cuda_largescaleknn_tpu.core.types import CandidateState
from mpi_cuda_largescaleknn_tpu.ops.candidates import (
    init_candidates,
    merge_candidates,
)
from mpi_cuda_largescaleknn_tpu.ops.distance import (
    elementwise_dist2,
    mxu_min_dim,
    norms2,
    score_tile,
    validate_score_dtype,
)
from mpi_cuda_largescaleknn_tpu.ops.partition import (
    BucketedPoints,
    nearest_first_order,
)


def _default_chunk(num_buckets: int, s: int, t: int,
                   budget_elems: int = 32_000_000) -> int:
    """Power-of-two query-bucket chunk keeping the [C, S, V*T] distance tile
    within ~``budget_elems`` f32 elements (~128 MB — bounds peak HBM
    traffic while keeping the sequential ``lax.map`` short: the round-3
    bench proved thousands of small serialized ops, not arithmetic, were
    the bottleneck)."""
    c = max(1, budget_elems // max(s * t, 1))
    c = 1 << int(math.log2(c))
    return max(1, min(num_buckets, c))


def _worst2(hd2: jnp.ndarray, qvalid: jnp.ndarray) -> jnp.ndarray:
    """Per-query-bucket squared prune radius: max over the bucket's real
    queries of their current k-th candidate dist2 (the tile-level analogue of
    the reference's managed-memory ``atomicMax`` radius,
    prePartitionedDataVariant.cu:91-94). -inf for all-padding buckets."""
    kth = hd2[:, :, -1]
    return jnp.max(jnp.where(qvalid, kth, -jnp.inf), axis=1)


def warm_start_self(q: BucketedPoints, k: int,
                    max_radius: float = jnp.inf) -> CandidateState:
    """Exact top-k of each query's OWN bucket, as the initial candidate
    state for a self-join traversal.

    The reference's cold heap fills during the first tree descent at no
    extra cost (one scalar insert per visited node,
    unorderedDataVariant.cu:86); the tile engines' fold instead pays up to
    k+1 extract-min passes over the first [S, V*T] chunk while a cold row
    adopts its first k candidates — ~k full-tile passes per query bucket,
    the dominant cost at k=100. Pre-folding the self bucket (each query's
    nearest neighborhood by construction: it shares the query's tight AABB)
    with one batched ``top_k``+merge fills every row exactly and shrinks
    the entry radius, so the traversal starts warm. Callers MUST then mask
    the self bucket out of the traversal (``skip_self``) — folding it twice
    would corrupt the candidate rows with duplicates.

    Semantics match the fold exactly: strict-< adoption against the
    ``max_radius`` cutoff slots (merge_candidates' stable existing-first
    sort), pad lanes carry +inf distance, self counts as neighbor 0.

    Candidate rows are independent, so a coarsened self-join (the
    ``point_group`` knob) simply passes ``coarsen_buckets(q, group)`` here:
    the returned rows are in the same flat order (the coarsening is a
    reshape) and each query pre-folds its containing coarse bucket — the
    traversal's skip mask must then use the same ``group``.
    """
    num_qb, s = q.ids.shape
    init = init_candidates(num_qb * s, k, max_radius)
    hd2 = init.dist2.reshape(num_qb, s, k)
    hidx = init.idx.reshape(num_qb, s, k)

    def one(args):
        pts, ids, cd2, cidx = args            # [S,D],[S],[S,k],[S,k]
        d2 = elementwise_dist2(pts, pts)      # [S, S]
        # pad lanes: PAD_SENTINEL coords already overflow to +inf, the
        # mask makes it explicit (and safe against sentinel changes)
        d2 = jnp.where(ids[None, :] >= 0, d2, jnp.inf)
        st = merge_candidates(CandidateState(cd2, cidx), d2,
                              jnp.broadcast_to(ids[None, :], d2.shape))
        return st.dist2, st.idx

    # sequential over buckets would serialize thousands of small ops (the
    # round-3 lesson); batch_size vmaps blocks of buckets per map step,
    # sized so the [batch, S, S] tile stays ~128MB whatever S is
    batch = max(1, min(64, num_qb, (1 << 25) // max(s * s, 1)))
    hd2, hidx = lax.map(one, (q.pts, q.ids, hd2, hidx), batch_size=batch)
    return CandidateState(hd2.reshape(num_qb * s, k),
                          hidx.reshape(num_qb * s, k))


def tile_schedule_slots(num_pb: int, visits_per_step: int = 8) -> int:
    """Visit slots in ONE query bucket's schedule, pad visits included —
    the per-query-bucket ceiling for tile-skip accounting. ``knn_update_tiled``
    counts ``chunk * V`` tiles for every step with >= 1 active bucket (the
    dense tile really covers the masked lanes), so a traversal of ``Bq``
    query buckets executes at most ``Bq * tile_schedule_slots(Bp)`` tiles;
    the shortfall is what pruning skipped (serve/engine.py's
    ``tiles_skipped`` counter)."""
    v = max(1, min(visits_per_step, num_pb))
    return -(-num_pb // v) * v


def knn_update_tiled(state: CandidateState, q: BucketedPoints,
                     p: BucketedPoints, *, chunk_buckets: int | None = None,
                     visits_per_step: int = 8, with_stats: bool | str = False,
                     skip_self=None, self_group: int = 1,
                     canonical_ties: bool = False,
                     score_dtype: str = "f32",
                     point_norms2=None,
                     prune_shrink: float = 1.0,
                     visit_frac: float = 1.0,
                     skip_rescore: bool = False):
    """Fold every real point of ``p`` into the candidate state (one
    reference ``runQuery`` launch, at bucket granularity).

    ``state`` rows are in ``q``'s bucket order: row ``b * S + i`` is query
    ``q.pts[b, i]``. Returns the updated state in the same order; with
    ``with_stats`` also an i32 count of [S, T] distance tiles actually
    computed (chunks skipped by the all-pruned ``lax.cond`` don't count),
    from which callers derive executed distance evaluations / FLOPs.
    ``with_stats="full"`` additionally returns the i32 count of chunk FOLDS
    executed — the number of ``merge_candidates`` sort-merges that actually
    ran (skipped chunks don't merge), the twin's analogue of the Pallas
    kernel's fold-pass counter.

    ``score_dtype``: ``"f32"`` (default) scores every tile with the exact
    elementwise VPU form; ``"bf16"`` scores with the matmul-form MXU
    expansion (ops/distance.py) — one bf16 dot_general per tile, f32
    accumulation — then rescores the top ``rescore_width(k)`` survivors
    per row with the exact f32 form before the merge, so the values
    reaching the candidate state are never approximate. ``point_norms2``
    optionally carries precomputed ``||p||^2`` per resident lane
    (f32[Bp, T] — the serving engine computes it once at index upload);
    ignored under f32.

    Each ``while_loop`` step visits ``visits_per_step`` point buckets per
    query bucket at once: one [C, S, V*T] distance tile and ONE width-2k
    merge per chunk instead of V of each. The per-(bucket, visit) prune
    mask keeps exactness — a bucket whose box distance is already beyond
    the query bucket's worst k-th candidate contributes only +inf rows.
    Round 3 proved the twin's bottleneck was thousands of small serialized
    ops, not arithmetic; V-batching plus the wider chunk budget cuts the
    sequential-op count by ~V * (new_budget / old_budget).

    ``skip_self``: traced i32/bool scalar; when nonzero, point bucket
    ``b // self_group`` is never folded into query bucket ``b`` — for
    self-joins whose heap was pre-filled by ``warm_start_self`` (``p``
    must then be ``coarsen_buckets`` of ``q``'s partition with the same
    ``self_group``, so bucket indices correspond).

    ``canonical_ties``: use the (dist2, idx) total order for equal-distance
    candidates (``merge_candidates(canonical=True)``) AND visit buckets
    whose box distance EQUALS the prune radius (``<=`` instead of ``<``).
    Together these make the result independent of the visit schedule — two
    different query bucketings of the same rows produce bit-identical
    candidate rows, which is the serving engine's multi-bucket exactness
    contract. The non-strict visit predicate is required for set-exactness:
    a bucket at box distance exactly equal to a row's k-th candidate
    distance can hold a TIED candidate with a smaller id that the canonical
    order must admit. (With the default fold-arrival discipline the same
    bucket is safely skippable — a tie never displaces — which is why the
    default keeps ``<``: identical results, strictly fewer visits.)

    ``prune_shrink`` / ``visit_frac`` / ``skip_rescore`` are the recall-SLO
    tier's APPROXIMATE truncation knobs (serve/recall.py), all trace-time
    statics so each plan is its own AOT program. ``prune_shrink < 1.0``
    tightens the kth-distance early exit: a bucket is visited only while
    its box distance is within ``prune_shrink`` of the query bucket's worst
    kth radius, so border buckets that could at best shave the candidate
    tail are skipped. ``visit_frac < 1.0`` hard-caps the nearest-first
    schedule at that fraction of its visit steps — the nearest buckets
    (where the mass of true neighbors lives) are always walked first, so
    the cap converts the schedule's tail into recall loss rather than
    uniform loss. ``skip_rescore`` forwards to ``score_tile`` (one-pass
    bf16, no exact rescore). At the defaults (1.0, 1.0, False) the traced
    program is IDENTICAL to the exact engine's — the exact path stays
    bitwise-stable by construction.
    """
    validate_score_dtype(score_dtype)
    if not 0.0 < prune_shrink <= 1.0:
        raise ValueError(f"prune_shrink must be in (0, 1], "
                         f"got {prune_shrink}")
    if not 0.0 < visit_frac <= 1.0:
        raise ValueError(f"visit_frac must be in (0, 1], got {visit_frac}")
    num_qb, s_q = q.ids.shape
    num_pb, s_p = p.ids.shape
    dim = q.pts.shape[-1]
    use_mxu = score_dtype == "bf16" and dim >= mxu_min_dim()
    k = state.dist2.shape[-1]

    v = max(1, min(visits_per_step, num_pb))
    chunk = chunk_buckets or _default_chunk(num_qb, s_q, s_p * v)
    assert num_qb % chunk == 0, (num_qb, chunk)
    n_chunks = num_qb // chunk

    sorted_d2, order = nearest_first_order(q.lower, q.upper,
                                           p.lower, p.upper)  # [Bq, Bp] x2
    # pad the schedule to a multiple of V: padded visits carry a
    # never-active box distance and a valid dummy index (bucket 0!) —
    # +inf normally, but NaN under canonical ties, whose <= predicate
    # would otherwise go live at +inf while a row's radius is still inf
    # and fold the dummy bucket a second time (NaN compares false under
    # both predicates; the early-exit cond only ever reads real slots)
    n_steps = -(-num_pb // v)
    pad_v = n_steps * v - num_pb
    if pad_v:
        pad_fill = jnp.nan if canonical_ties else jnp.inf
        sorted_d2 = jnp.concatenate(
            [sorted_d2, jnp.full((num_qb, pad_v), pad_fill, sorted_d2.dtype)],
            axis=1)
        order = jnp.concatenate(
            [order, jnp.zeros((num_qb, pad_v), order.dtype)], axis=1)

    qvalid = q.ids >= 0
    hd2 = state.dist2.reshape(num_qb, s_q, k)
    hidx = state.idx.reshape(num_qb, s_q, k)

    q_chunked = q.pts.reshape(n_chunks, chunk, s_q, dim)
    if use_mxu:
        # per-lane ||p||^2, exact f32 — precomputed once at upload by the
        # serving engine, derived here otherwise (pad lanes overflow to
        # +inf, so they can never win the survivor top_k)
        pn2_all = (jnp.asarray(point_norms2, jnp.float32)
                   if point_norms2 is not None else norms2(p.pts))  # [Bp,T]

    def live(box_d2, radius2):
        # canonical mode must VISIT buckets tied exactly at the prune radius
        # (they can hold equal-distance candidates the (d2, id) order
        # admits); the default's strict < skips them — a tie never
        # displaces under fold-arrival order, so skipping is free there.
        # The approximate tier shrinks the radius at trace time; the
        # branch keeps the exact (shrink=1) jaxpr byte-identical
        if prune_shrink < 1.0:
            radius2 = radius2 * jnp.float32(prune_shrink)
        return box_d2 <= radius2 if canonical_ties else box_d2 < radius2

    # approximate visit cap: walk at most this many nearest-first steps
    # (>= 1 so every query always folds its nearest point buckets)
    n_steps_max = (n_steps if visit_frac >= 1.0
                   else max(1, int(math.ceil(n_steps * visit_frac))))

    def cond(carry):
        _hd2, _hidx, worst2, step, _tiles, _folds = carry
        next_d2 = lax.dynamic_index_in_dim(sorted_d2, jnp.minimum(
            step * v, num_pb - 1), axis=1, keepdims=False)
        return (step < n_steps_max) & jnp.any(live(next_d2, worst2))

    def body(carry):
        hd2, hidx, worst2, step, tiles, folds = carry
        visit = lax.dynamic_slice_in_dim(order, step * v, v, axis=1)
        visit_d2 = lax.dynamic_slice_in_dim(sorted_d2, step * v, v, axis=1)
        active = live(visit_d2, worst2[:, None])                 # [Bq, V]
        if skip_self is not None:
            own = (jnp.arange(num_qb, dtype=visit.dtype)
                   // self_group)[:, None]
            active &= ~((visit == own) & (jnp.asarray(skip_self) != 0))
        pts_v = p.pts[visit]                                     # [Bq,V,T,D]
        ids_v = p.ids[visit]                                     # [Bq,V,T]
        ops = [q_chunked,
               pts_v.reshape(n_chunks, chunk, v, s_p, dim),
               ids_v.reshape(n_chunks, chunk, v, s_p),
               active.reshape(n_chunks, chunk, v),
               hd2.reshape(n_chunks, chunk, s_q, k),
               hidx.reshape(n_chunks, chunk, s_q, k)]
        if use_mxu:
            ops.append(pn2_all[visit].reshape(n_chunks, chunk, v, s_p))

        def chunk_fn(args):
            qp, pp, pid, act, cd2, cidx = args[:6]
            pn2c = args[6] if use_mxu else None

            def compute(_):
                # [C, S, V*T] score tile against the V gathered buckets —
                # exact elementwise (VPU) or matmul-form bf16 score + exact
                # f32 rescore of the survivors (MXU), ops/distance.py
                ppf = pp.reshape(chunk, v * s_p, dim)
                mask = jnp.broadcast_to(
                    act[:, None, :, None],
                    (chunk, 1, v, s_p)).reshape(chunk, 1, v * s_p)
                d2, ids = score_tile(
                    qp, ppf, pid.reshape(chunk, v * s_p), k,
                    score_dtype=score_dtype, mask=mask,
                    pn2=pn2c.reshape(chunk, v * s_p) if use_mxu else None,
                    skip_rescore=skip_rescore)
                w = d2.shape[-1]
                st = merge_candidates(
                    CandidateState(cd2.reshape(chunk * s_q, k),
                                   cidx.reshape(chunk * s_q, k)),
                    d2.reshape(chunk * s_q, w),
                    ids.reshape(chunk * s_q, w),
                    canonical=canonical_ties)
                return (st.dist2.reshape(chunk, s_q, k),
                        st.idx.reshape(chunk, s_q, k))

            # chunks whose buckets are ALL pruned this step skip the tile
            # entirely (lax.map runs chunks sequentially, so the cond branch
            # is real skipped work, not a select) — recovers most of the
            # lock-step waste in late rounds when few stragglers remain
            return lax.cond(jnp.any(act), compute,
                            lambda _: (cd2, cidx), None)

        hd2, hidx = lax.map(chunk_fn, tuple(ops))
        hd2 = hd2.reshape(num_qb, s_q, k)
        hidx = hidx.reshape(num_qb, s_q, k)
        # tiles executed this step: skipped chunks contribute 0, a computed
        # chunk contributes its full chunk*V tiles (masked-out buckets in
        # an active chunk still burn VPU work — count what ran, not what
        # was useful); folds counts the chunk merges that actually ran
        act_c = active.reshape(n_chunks, chunk * v)
        ran = jnp.any(act_c, axis=1)
        tiles = tiles + jnp.sum(
            jnp.where(ran, chunk * v, 0)).astype(jnp.int32)
        folds = folds + jnp.sum(ran).astype(jnp.int32)
        return hd2, hidx, _worst2(hd2, qvalid), step + 1, tiles, folds

    # derive the zero from the heap so the counter carries the same
    # varying-manual-axes type as the rest of the carry under shard_map
    # (a fresh constant would be replicated and trip the vma checker);
    # a comparison, not a multiply: hd2 starts at cutoff^2 = inf by default
    # and inf * 0 is NaN, whose int cast is backend-defined
    tiles0 = (hd2[0, 0, 0] < 0).astype(jnp.int32)
    init = (hd2, hidx, _worst2(hd2, qvalid), jnp.int32(0), tiles0, tiles0)
    hd2, hidx, _, _, tiles, folds = lax.while_loop(cond, body, init)
    out = CandidateState(hd2.reshape(num_qb * s_q, k),
                         hidx.reshape(num_qb * s_q, k))
    if with_stats == "full":
        # folds = chunk sort-merges actually executed (a REAL counter, the
        # twin's analogue of the Pallas fold-pass count — one width-2k
        # merge per non-pruned chunk per step)
        return out, tiles, folds
    return (out, tiles) if with_stats else out
