"""Left-balanced implicit kd-tree construction as XLA sort passes.

TPU-native equivalent of ``cukd::buildTree(float3*, int N)`` (called at
unorderedDataVariant.cu:161 and prePartitionedDataVariant.cu:271): an
**in-place, pointer-free** kd-tree where the reordered point array *is* the
tree — node ``i``'s children live at ``2i+1`` / ``2i+2``, every node is a
point, the tree is complete and left-balanced, and the split dimension is
round-robin by depth (``depth % 3``), so no per-node metadata exists at all.

Algorithm (same complexity class as the GPU builder described in Wald,
*GPU-friendly left-balanced k-d tree construction*, arXiv:2211.00120, but
expressed as whole-array ops XLA:TPU is good at):

  repeat ceil(log2(N+1)) times, once per tree level L:
    1. sort all points by (current-node-tag, coordinate along L % 3)
       — one multi-operand ``lax.sort``; finalized points have unique tags
       and ride along inertly;
    2. per contiguous tag segment of size n, the element at the segment's
       left-balanced pivot rank F(n) becomes that node's point (its tag is
       final); elements before it re-tag to child 2t+1, after it to 2t+2.

  finally scatter each point to array slot == its tag.

Everything is sort + searchsorted + elementwise — no scalar loops, no dynamic
shapes, fully jittable and differentiable-by-construction irrelevant (pure
integer/gather work).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def left_subtree_size(n: jnp.ndarray) -> jnp.ndarray:
    """Number of nodes in the left subtree of a complete left-balanced binary
    tree with ``n`` nodes (vectorized, int32).

    With h = floor(log2(n)) and half = 2**(h-1):
    F = (half - 1) + clamp(n - (2*half - 1), 0, half).
    """
    n = n.astype(jnp.int32)
    h = 31 - jax.lax.clz(jnp.maximum(n, 1))
    half = jnp.where(h >= 1, jnp.left_shift(jnp.int32(1), jnp.maximum(h - 1, 0)), 0)
    f = (half - 1) + jnp.clip(n - (2 * half - 1), 0, half)
    return jnp.where(n <= 1, 0, f)


def node_depth(i: jnp.ndarray) -> jnp.ndarray:
    """Depth of node index ``i`` in the implicit tree: floor(log2(i+1))."""
    return 31 - jax.lax.clz(i.astype(jnp.int32) + 1)


def build_tree(points: jnp.ndarray, point_ids: jnp.ndarray | None = None):
    """Build the implicit left-balanced kd-tree.

    Args:
      points: f32[N, 3] (sentinel padding rows allowed — they are ordinary
        far-away points and end up in far subtrees).
      point_ids: optional i32[N] original identities to carry through the
        permutation (the reference discards these; we keep them so neighbor
        *indices* can be reported, a capability the reference computes but
        throws away — unorderedDataVariant.cu:228 region).

    Returns:
      (tree f32[N,3], tree_ids i32[N]): tree[i] is node i's point.
    """
    points = jnp.asarray(points, jnp.float32)
    n_total = points.shape[0]
    if point_ids is None:
        point_ids = jnp.arange(n_total, dtype=jnp.int32)
    point_ids = jnp.asarray(point_ids, jnp.int32)
    if n_total == 0:
        return points, point_ids
    num_levels = max(1, math.ceil(math.log2(n_total + 1)))

    tags = jnp.zeros((n_total,), jnp.int32)
    x, y, z = points[:, 0], points[:, 1], points[:, 2]
    ids = point_ids
    positions = jnp.arange(n_total, dtype=jnp.int32)

    for level in range(num_levels):
        dim = level % 3
        coord = (x, y, z)[dim]
        tags, _, x, y, z, ids = jax.lax.sort(
            (tags, coord, x, y, z, ids), num_keys=2, is_stable=True)
        seg_start = jnp.searchsorted(tags, tags, side="left").astype(jnp.int32)
        seg_end = jnp.searchsorted(tags, tags, side="right").astype(jnp.int32)
        seg_n = seg_end - seg_start
        rank = positions - seg_start
        pivot = left_subtree_size(seg_n)
        level_min = (1 << level) - 1
        active = tags >= level_min  # segments not yet finalized = this level's
        new_tags = jnp.where(rank < pivot, 2 * tags + 1,
                             jnp.where(rank == pivot, tags, 2 * tags + 2))
        tags = jnp.where(active, new_tags, tags)

    tree = jnp.zeros_like(points)
    tree = tree.at[tags, 0].set(x).at[tags, 1].set(y).at[tags, 2].set(z)
    tree_ids = jnp.zeros_like(ids).at[tags].set(ids)
    return tree, tree_ids
