"""Persistent per-query top-k candidate lists.

TPU-native re-design of ``cukd::FlexHeapCandidateList`` (the reference's
per-query GPU max-heap over packed ``uint64`` (dist2,idx) entries, constructed
at unorderedDataVariant.cu:84-85 and reopened at :97). Semantics preserved:

- **Fresh init with cutoff** (reference: constructor with ``cutoff >= 0``,
  round 0 at unorderedDataVariant.cu:84-85): all k slots hold
  ``max_radius**2`` with idx -1. A candidate enters only by being strictly
  closer than the current worst slot, so nothing at or beyond ``max_radius``
  is ever recorded — the ``-r`` search-radius bound.
- **Adopt across rounds** (reference: ``cutoff == -1.f`` for rounds > 0):
  the state simply persists; merging new candidates into the same arrays *is*
  the cross-rank top-k merge. No special flag needed functionally.
- **Extraction** (reference ``extractFinalResult``,
  unorderedDataVariant.cu:89-103): result is ``sqrt`` of the k-th smallest
  dist2; if fewer than k candidates were ever found the k-th slot still holds
  the init value (``inf`` without ``-r``) and the output stays ``inf``.
- **Worst-radius reduction** (reference: per-thread
  ``cukd::atomicMax(pMaxRadius, sqrt(cl.maxRadius2()))``,
  prePartitionedDataVariant.cu:91-94): a masked ``jnp.max`` over the k-th
  column — no atomics on TPU.

Layout: SoA ``(f32[Q,k] dist2 ascending, i32[Q,k] idx)`` instead of a packed
u64 heap. Sorted-ascending rows make the merge a (stable) sort-and-slice,
which maps onto XLA's vectorized sorts; a binary heap's pointer-chasing would
not vectorize on the VPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from mpi_cuda_largescaleknn_tpu.core.types import CandidateState


def init_candidates(num_queries: int, k: int, max_radius=jnp.inf) -> CandidateState:
    """Fresh candidate state bounded by ``max_radius`` (f32 semantics:
    slots hold ``float32(max_radius)**2``).

    ``max_radius`` may also be a per-query ``f32[num_queries]`` array:
    row q's slots then hold ``max_radius[q]**2`` — the serving engine's
    certified radius seeding (serve/qcache.py), where a cached answer's
    triangle-inequality bound tightens ONE row's prune. As an array it
    is a runtime operand, not a trace-time constant, so every radius
    vector shares one compiled program (the AOT bucket keys stay flat).
    The strict-< adoption semantics are per-row unchanged: a candidate
    at or beyond that row's radius is never recorded."""
    r = jnp.asarray(max_radius, jnp.float32)
    if r.ndim == 0:
        dist2 = jnp.full((num_queries, k), r * r, dtype=jnp.float32)
    else:
        if r.shape != (num_queries,):
            raise ValueError(f"per-query max_radius must be "
                             f"[{num_queries}], got {r.shape}")
        dist2 = jnp.broadcast_to((r * r)[:, None], (num_queries, k))
    idx = jnp.full((num_queries, k), -1, dtype=jnp.int32)
    return CandidateState(dist2, idx)


def merge_candidates(state: CandidateState, cand_dist2: jnp.ndarray,
                     cand_idx: jnp.ndarray,
                     canonical: bool = False) -> CandidateState:
    """Merge a tile of candidates ``(f32[Q,T], i32[Q,T])`` into the state.

    Keeps the k smallest of the union per row. Stable ordering with existing
    entries first reproduces the heap's strict-< insertion: a candidate tied
    with the current worst slot does not displace it — equal-distance
    candidates therefore keep FOLD-ARRIVAL order, which depends on the
    caller's visit schedule.

    ``canonical=True`` switches the tie discipline to the total order
    (dist2, idx): rows come out ascending by distance THEN id, and the kept
    set at the k-boundary is the k smallest under that order — so the merged
    result is independent of the order in which tiles were folded (any two
    fold schedules over the same candidates produce bit-identical rows).
    The serving engine's multi-bucket traversal requires this: different
    query-bucket geometries visit point buckets in different orders, and the
    canonical order is what makes them bitwise comparable
    (tests/test_query_locality.py). Init slots still win their ties
    (``idx == -1`` sorts before every real id at the cutoff distance), so
    strict-< adoption against ``max_radius`` is preserved. The boundary
    tie-fix runs the id selection through a f32 ``top_k`` (XLA:CPU lowers
    integer TopK to a scalar loop ~7x slower), so ids must stay below 2**24
    to round-trip exactly — callers gate on index size
    (serve/engine.py)."""
    k = state.dist2.shape[1]
    t = cand_dist2.shape[1]
    if t > k:
        # pre-reduce the tile to its own k best to keep the sort width at 2k
        neg, pos = jax.lax.top_k(-cand_dist2, k)
        v = -neg
        ids = jnp.take_along_axis(cand_idx, pos, axis=1)
        if canonical:
            # top_k resolves ties by lane, which may DROP a tied candidate
            # with a smaller id at the tile's k-boundary. The boundary class
            # is the trailing block of v (ascending, kth = max); replace its
            # ids with the smallest ids among ALL lanes tied at kth. Guarded
            # by a cond: boundary ties are rare in real float data, so the
            # common case pays one elementwise scan, not a second top_k.
            # (d2 == inf ties need no fix: (inf, id>=0) never displaces the
            # init slots' (inf, -1) under the 2-key sort below.)
            kth = v[:, k - 1:k]
            # kth is an element of cand_dist2/v, so the boundary tie class
            # is DEFINED by bitwise equality — deliberate float ==:
            tied_lane = cand_dist2 == kth  # lsk: allow[float-eq] tie class
            tied_out = v == kth  # lsk: allow[float-eq] tie class
            tcount = jnp.sum(tied_out, axis=1)
            needs = jnp.any((jnp.sum(tied_lane, axis=1) > tcount)
                            & jnp.isfinite(kth[:, 0]))

            def fix(ids):
                tidf = jnp.where(tied_lane, cand_idx.astype(jnp.float32),
                                 jnp.inf)
                tneg, _ = jax.lax.top_k(-tidf, k)
                tl = -tneg  # ascending tied ids (inf-padded)
                j = jax.lax.broadcasted_iota(jnp.int32, v.shape, 1)
                rank = jnp.clip(j - (k - tcount[:, None]), 0, k - 1)
                picked = jnp.take_along_axis(tl, rank, axis=1)
                return jnp.where(tied_out & jnp.isfinite(kth),
                                 picked.astype(jnp.int32), ids)

            ids = jax.lax.cond(needs, fix, lambda i: i, ids)
        cand_dist2, cand_idx = v, ids
    cat_d2 = jnp.concatenate([state.dist2, cand_dist2], axis=1)
    cat_idx = jnp.concatenate([state.idx, cand_idx], axis=1)
    sorted_d2, sorted_idx = jax.lax.sort((cat_d2, cat_idx),
                                         num_keys=2 if canonical else 1,
                                         dimension=1, is_stable=True)
    return CandidateState(sorted_d2[:, :k], sorted_idx[:, :k])


def tree_merge_candidates(state: CandidateState, axis: str,
                          num_shards: int) -> CandidateState:
    """Cross-shard top-k all-reduce inside ``shard_map``: every device ends
    with the global top-k of the ``num_shards`` per-shard candidate states.

    log2(R) recursive-doubling rounds: in round s each device exchanges its
    running state with the device whose index differs in bit s (one
    ``ppermute`` whose permutation is its own inverse — both directions of
    every link carry state simultaneously, the same full-duplex discipline
    as the ring's counter-rotating copies) and folds the arriving state
    through ``merge_candidates``. Operand order is the whole tie contract:
    the state covering the LOWER shard-index block is always the left
    (existing) operand, so ``merge_candidates``' stable sort resolves equal
    distances in ascending (shard, slot) order — bit-identical to the host
    merge's stable argsort over shard-major concatenated candidate rows
    (serve/engine.py ``_merge_shard_candidates``).

    Truncation to k per round loses nothing: any global top-k entry is in
    the top-k of every union that contains it. Requires power-of-two
    ``num_shards`` (the recursive-doubling blocks must tile the axis;
    ``resolve_merge`` in parallel/ring.py falls back to the host merge
    otherwise). R == 1 is the identity.
    """
    if num_shards & (num_shards - 1):
        raise ValueError(
            f"tree merge needs a power-of-two shard count, got {num_shards}")
    me = jax.lax.axis_index(axis)
    step = 1
    while step < num_shards:
        perm = [(i, i ^ step) for i in range(num_shards)]
        other_d2 = jax.lax.ppermute(state.dist2, axis, perm)
        other_idx = jax.lax.ppermute(state.idx, axis, perm)
        # my current block is [me & ~(2*step - 1), +step) or the one above:
        # bit s of the device index says which; the lower block merges first
        mine_lower = (me & step) == 0
        first = CandidateState(
            jnp.where(mine_lower, state.dist2, other_d2),
            jnp.where(mine_lower, state.idx, other_idx))
        second_d2 = jnp.where(mine_lower, other_d2, state.dist2)
        second_idx = jnp.where(mine_lower, other_idx, state.idx)
        state = merge_candidates(first, second_d2, second_idx)
        step <<= 1
    return state


def extract_final_result(state: CandidateState) -> jnp.ndarray:
    """k-th-NN distance per query: ``sqrt(kth smallest dist2)``; stays ``inf``
    when fewer than k neighbors were found (reference
    unorderedDataVariant.cu:97-102; ``sqrt(inf) == inf`` so no branch)."""
    return jnp.sqrt(state.dist2[:, -1])


def current_worst_radius(state: CandidateState, valid_mask: jnp.ndarray | None = None) -> jnp.ndarray:
    """Max over (real) queries of their current k-th candidate distance — the
    pruning cutoff the prepartitioned reference maintains in managed memory
    via ``atomicMax`` (prePartitionedDataVariant.cu:91-94,297-298)."""
    kth = state.dist2[:, -1]
    if valid_mask is not None:
        kth = jnp.where(valid_mask, kth, -jnp.inf)
    # clamp: a shard with zero real queries must yield 0 (prune everything),
    # not sqrt(-inf) = nan, which would poison pruning comparisons
    return jnp.sqrt(jnp.maximum(jnp.max(kth), 0.0))
