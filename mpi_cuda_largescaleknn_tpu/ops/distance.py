"""Shared distance scorers: elementwise (VPU) and matmul-form (MXU).

Every distance tile in this package used to be computed elementwise —
``(dx*dx + dy*dy) + dz*dz`` broadcast over a [Q, T] tile — which is
perfectly regular VPU work but leaves the MXU (the overwhelming majority
of a TPU's FLOP/s) idle on the hot path, and hardwires D=3. TPU-KNN
(arXiv:2206.14286) shows the fix: expand

    ||q - p||^2 = ||q||^2 + ||p||^2 - 2 q.p

so the dominant term is ONE dense [Q, D] x [D, T] matmul per tile. The
cross term is scored in bf16 (f32 accumulation — the MXU's native mode);
the norms stay exact f32. The catch is exactness: the expansion's
cancellation error is unbounded relative to the direct form (a pair
separated by less than a bf16 ulp at large ||p|| scores identically), so
the bf16 scores are used ONLY to select survivors — the top
``rescore_width(k)`` lanes per row — which are then rescored with the
exact elementwise f32 form before they ever reach ``merge_candidates``.
Final (dist2, idx) results are bit-identical to the elementwise kernel
whenever the true top-k of a tile lands inside the survivor window (the
default window is 2k wide; see docs/TUNING.md "Distance kernel" for when
that holds and when it cannot).

Both forms are D-generic: the elementwise scorer reduces components in a
fixed left-to-right order, so at D=3 it is the exact expression tree
``(d0*d0 + d1*d1) + d2*d2`` the kernels always used — swapping call sites
onto this module changes no bits.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

#: score dtypes the kernels accept: "f32" = exact elementwise on the VPU
#: (the default, and the only mode with an unconditional exactness proof);
#: "bf16" = matmul-form MXU scoring + exact f32 rescore of the survivors.
SCORE_DTYPES = ("f32", "bf16")


def validate_score_dtype(score_dtype: str) -> str:
    if score_dtype not in SCORE_DTYPES:
        raise ValueError(f"unknown score_dtype '{score_dtype}' "
                         f"(expected one of {SCORE_DTYPES})")
    return score_dtype


def opaque_one(like: jnp.ndarray) -> jnp.ndarray:
    """A runtime-opaque f32 ``1.0`` — the FMA-contraction guard.

    XLA:CPU freely contracts ``di*di + acc`` into one fused multiply-add,
    and it does so DIFFERENTLY per fusion context (a [Q, T] broadcast tile
    vs a [Q, W] gathered rescore of the very same pairs came out +-1 ulp
    apart in round-6 measurements). The exactness contract of this module —
    the survivor rescore reproduces the elementwise tile BIT FOR BIT — needs
    every exact-distance site to round every op the same way, so each square
    is multiplied by this value: ``x * 1.0 == x`` exactly under IEEE-754,
    and because the 1.0 here is DERIVED FROM RUNTIME DATA (``v*0 + 1`` of
    ``like``'s first element, which no strict-IEEE compiler may fold — ``v``
    could be non-finite for all it knows) the multiply survives into
    codegen and the following add has a multiply-by-opaque (never a raw
    square) as its operand — un-contractible. The results are then the
    correctly-rounded sequential values (= the numpy oracle's), identical
    in every context. (An ``optimization_barrier``-hidden constant does NOT
    work: XLA's barrier expander strips it before codegen and the 1.0 folds
    right back.) ``like`` must be finite, which every coordinate in this
    package is — PAD_SENTINEL included."""
    v = jnp.asarray(like, jnp.float32).reshape(-1)[0]
    return v * jnp.float32(0.0) + jnp.float32(1.0)


def accumulate_sq(acc, di, one):
    """One guarded square-accumulate step: ``acc + (di*di)*one`` with the
    fixed left-to-right association every exact scorer in this package
    uses. ``one`` is ``opaque_one()`` (or any runtime-opaque 1.0 — the
    Pallas kernels derive theirs from ``program_id``, which Mosaic can
    lower where the barrier cannot). ``acc=None`` starts the chain."""
    sq = (di * di) * one
    return sq if acc is None else acc + sq


def norms2(pts: jnp.ndarray) -> jnp.ndarray:
    """f32[..., D] -> f32[...]: squared norm, fixed left-to-right component
    order (the precomputed ||p||^2 term of the matmul expansion — exact f32,
    never bf16: only the cross term is approximated)."""
    acc = pts[..., 0] * pts[..., 0]
    for i in range(1, pts.shape[-1]):
        acc = acc + pts[..., i] * pts[..., i]
    return acc


def elementwise_dist2(q: jnp.ndarray, p: jnp.ndarray) -> jnp.ndarray:
    """Exact squared distances f32[..., Q, D] x f32[..., T, D] ->
    f32[..., Q, T], fixed left-to-right component order — at D=3 the exact
    ``(dx*dx + dy*dy) + dz*dz`` expression tree of the original kernels
    (NOT the matmul expansion, whose cancellation error is unbounded).
    Every step carries the ``opaque_one`` contraction guard, so the values
    are the correctly-rounded sequential ones in every fusion context."""
    d = q.shape[-1]
    one = opaque_one(q)
    acc = None
    for i in range(d):
        di = q[..., :, None, i] - p[..., None, :, i]
        acc = accumulate_sq(acc, di, one)
    return acc


def mxu_min_dim() -> int:
    """Smallest point dimensionality at which ``score_dtype="bf16"``
    actually engages the matmul-form scorer; below it the exact elementwise
    path IS the fast path (at D=3 the MXU would run at 3/128 utilization
    and the survivor-selection machinery is pure overhead — the CPU-fixture
    crossover measured at D~16, kernel_compare in BENCH_serve.json), so
    requesting bf16 there silently scores exactly. ``LSK_MXU_MIN_DIM``
    overrides (trace-time; the parity tests pin it to 1 to exercise the
    MXU machinery at every D)."""
    try:
        v = int(os.environ.get("LSK_MXU_MIN_DIM", "") or 0)
    except ValueError:
        v = 0                       # a bad sweep value must tune, not crash
    return v if v > 0 else 16


def rescore_width(k: int, t: int) -> int:
    """bf16 survivor window per row: how many approx-top lanes of a width-
    ``t`` tile get the exact f32 rescore. Default ``max(2k, 16)`` — wide
    enough that a true top-k candidate is dropped only when more than
    ``width - k`` tile lanes score within bf16 error of the k-th distance
    (docs/TUNING.md). ``LSK_RESCORE_WIDTH`` overrides (trace-time, like the
    kernel-geometry env knobs)."""
    try:
        w = int(os.environ.get("LSK_RESCORE_WIDTH", "") or 0)
    except ValueError:
        w = 0                       # a bad sweep value must tune, not crash
    if w <= 0:
        w = max(2 * k, 16)
    return min(t, max(w, k))


def split_bf16(x: jnp.ndarray):
    """Split f32 into (hi, lo) bf16 terms with ``hi + lo ~= x`` to ~16
    mantissa bits — the standard bf16x3 precision-recovery decomposition
    for MXU matmuls."""
    hi = x.astype(jnp.bfloat16)
    lo = (x - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    return hi, lo


def mxu_scores(q: jnp.ndarray, p: jnp.ndarray,
               pn2: jnp.ndarray | None = None,
               qn2: jnp.ndarray | None = None) -> jnp.ndarray:
    """Approximate squared distances via the matmul expansion: the cross
    term rides the MXU as THREE bf16 dot_generals with f32 accumulation
    (the bf16x3 split — hi.hi + hi.lo + lo.hi — carrying ~16 mantissa
    bits); the norms ride exact f32. One-pass bf16 was measured missing
    true top-k members in 15% of rows on the serving fixture (absolute
    error ~||p||*2^-9 swamps the inter-candidate gaps); the split brings
    the error to ~scale*2^-16, far below any non-adversarial gap, so the
    default survivor window holds. Shapes as ``elementwise_dist2``;
    ``pn2``/``qn2`` accept precomputed norms (per-bucket ||p||^2 is
    computed once at index upload by the serving engine)."""
    if qn2 is None:
        qn2 = norms2(q)
    if pn2 is None:
        pn2 = norms2(p)
    qh, ql = split_bf16(q)
    ph, plo = split_bf16(jnp.swapaxes(p, -1, -2))
    cross = (jnp.matmul(qh, ph, preferred_element_type=jnp.float32)
             + jnp.matmul(qh, plo, preferred_element_type=jnp.float32)
             + jnp.matmul(ql, ph, preferred_element_type=jnp.float32))
    return qn2[..., :, None] + pn2[..., None, :] - 2.0 * cross


def score_tile(q: jnp.ndarray, p: jnp.ndarray, pid: jnp.ndarray, k: int, *,
               score_dtype: str = "f32", mask: jnp.ndarray | None = None,
               pn2: jnp.ndarray | None = None, skip_rescore: bool = False):
    """Score one distance tile, ready for ``merge_candidates``.

    Args:
      q: f32[..., Q, D] queries. p: f32[..., T, D] points (shared across the
        tile's Q rows). pid: i32[..., T] point ids, broadcastable against
        the [..., Q, T] score tile. mask: optional bool broadcastable to
        [..., Q, T]; False lanes can never be adopted (their distances are
        forced to +inf — in BOTH modes, including after the rescore).
      pn2: optional precomputed f32[..., T] squared point norms (bf16 mode).
      skip_rescore: approximate one-pass mode (the recall-SLO tier's knob,
        serve/recall.py): under bf16 at D >= ``mxu_min_dim`` the raw
        matmul-form scores are fed straight to the merge — no survivor
        top_k, no exact rescore — trading the bf16x3 error bound
        (~scale * 2^-16) for the cost of the selection machinery. Scores
        are clamped at 0 (the expansion can cancel slightly negative).
        Below the MXU threshold the elementwise path is exact AND fastest,
        so the knob is a no-op there by design.

    Returns ``(cand_d2, cand_idx)``:

    - ``score_dtype="f32"``: the full exact elementwise tile, width T —
      exactly what the kernels always fed their merges.
    - ``score_dtype="bf16"``: width ``rescore_width(k, T)``. The matmul-form
      bf16 scores pick the survivors per row; survivor lane indices are
      re-sorted ASCENDING so the tile fed to the merge is a subsequence of
      the original lane order (fold-arrival tie discipline preserved), and
      every survivor's distance is recomputed with the exact elementwise f32
      form — values reaching the candidate state are never approximate.
    """
    validate_score_dtype(score_dtype)
    t = p.shape[-2]
    w = rescore_width(k, t)
    if (score_dtype == "f32" or q.shape[-1] < mxu_min_dim()
            or (w >= t and not skip_rescore)):
        # exact full-width tile (also the bf16 fallback below the MXU
        # dimensionality threshold, and when the survivor window would
        # cover every lane anyway — then the top_k buys nothing)
        d2 = elementwise_dist2(q, p)
        if mask is not None:
            d2 = jnp.where(mask, d2, jnp.inf)
        idx = jnp.broadcast_to(pid[..., None, :], d2.shape)
        return d2, idx

    scores = mxu_scores(q, p, pn2=pn2)
    if mask is not None:
        scores = jnp.where(mask, scores, jnp.inf)
    if skip_rescore:
        # one-pass approximate tile: full width, raw expansion scores
        # (masked lanes stay +inf; the 0-clamp keeps sqrt() downstream
        # finite when cancellation dips a self-pair slightly negative)
        idx = jnp.broadcast_to(pid[..., None, :], scores.shape)
        return jnp.maximum(scores, jnp.float32(0.0)), idx
    _neg, pos = jax.lax.top_k(-scores, w)               # [..., Q, W]
    # restore lane order: the survivors must reach the merge as a
    # subsequence of the tile's original lanes, or equal-distance
    # candidates would change fold-arrival order vs the elementwise kernel
    pos = jax.lax.sort(pos, dimension=pos.ndim - 1)
    # gather survivor coordinates ([..., 1, T, D] x [..., Q, W, 1] -> the
    # gather broadcasts over Q and D) and rescore them exactly — the
    # guarded recipe makes these bits EQUAL to elementwise_dist2's
    pg = jnp.take_along_axis(p[..., None, :, :], pos[..., None], axis=-2)
    one = opaque_one(q)
    acc = None
    for i in range(q.shape[-1]):
        acc = accumulate_sq(acc, q[..., :, None, i] - pg[..., i], one)
    # gather ids/mask THROUGH broadcasting ([..., 1, T] against the
    # [..., Q, W] positions) — materializing full [..., Q, T] copies first
    # measurably dominated the D=3 tile cost
    idx = jnp.take_along_axis(pid[..., None, :], pos, axis=-1)
    if mask is not None:
        # a masked lane selected only because too few lanes were live must
        # stay +inf — its EXACT distance may be finite (pruned buckets hold
        # real points), and adopting it would break the prune's exactness
        if mask.ndim >= 2 and mask.shape[-2] == 1:   # per-tile mask rows
            keep = jnp.take_along_axis(
                jnp.broadcast_to(mask, scores.shape[:-2] + (1, t)),
                pos, axis=-1)
        else:                                        # per-query mask rows
            keep = jnp.take_along_axis(
                jnp.broadcast_to(mask, scores.shape), pos, axis=-1)
        acc = jnp.where(keep, acc, jnp.inf)
    return acc, idx
