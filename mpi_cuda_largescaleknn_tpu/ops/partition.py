"""Spatial bucketing: median-split partition into contiguous leaf tiles.

This is the top of the kd-tree re-expressed for a tile machine. The
reference's per-point implicit tree (``cukd::buildTree``,
unorderedDataVariant.cu:161) exists so one scalar GPU thread can walk
point-by-point; a TPU wants *tile*-granular structure instead: the point set
is recursively median-split (widest-extent dimension, L levels of one
``lax.sort`` each — the same sort-dominated complexity class as the GPU
builder, arXiv:2211.00120) into ``B = 2^L`` equal-size contiguous buckets,
each with a tight AABB over its real points. The bucketed array plus bounds
IS the tree: traversal becomes "visit buckets nearest-first, prune on box
distance" (ops/tiled.py), which is the same pruning predicate the
reference's traversal applies per node and its demand engine applies per
rank (``computeDistance``/``computeMyPeer``, prePartitionedDataVariant.cu:
150-174) — evaluated at VPU-tile granularity.

Sentinel padding rows (PAD_SENTINEL coords) sort above every real
coordinate, so they accumulate in the trailing buckets; AABBs mask them out,
leaving empty buckets with inverted (+inf/-inf) bounds that any box-distance
computation reports as infinitely far — never visited.
"""

from __future__ import annotations

import functools
import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from mpi_cuda_largescaleknn_tpu.core.types import PAD_SENTINEL
from mpi_cuda_largescaleknn_tpu.utils.math import cdiv, next_pow2, round_up


class BucketedPoints(NamedTuple):
    """A point shard in bucket-contiguous order plus its per-bucket bounds.

    ``pts`` rows within one bucket are spatially coherent; ``ids`` carry
    global point identities (-1 = padding); ``pos`` maps each bucketed row
    back to its row in the *input* array (-1 = padding) so results computed
    in bucket order can be scattered back.
    """

    pts: jnp.ndarray    # f32[B, S, D]
    ids: jnp.ndarray    # i32[B, S]
    lower: jnp.ndarray  # f32[B, D] (+inf rows for empty buckets)
    upper: jnp.ndarray  # f32[B, D] (-inf rows for empty buckets)
    pos: jnp.ndarray    # i32[B, S] row index into the input array, -1 = pad

    @property
    def num_buckets(self) -> int:
        return self.pts.shape[0]

    @property
    def bucket_size(self) -> int:
        return self.pts.shape[1]


def choose_buckets(n: int, bucket_size_target: int) -> tuple[int, int]:
    """(B, S): B = power-of-two bucket count, S = padded bucket size
    (multiple of 8 sublanes) with B*S >= n and S close to the target."""
    b = next_pow2(max(1, cdiv(n, max(bucket_size_target, 1))))
    s = round_up(max(cdiv(n, b), 1), 8)
    return b, s


@functools.partial(jax.jit, static_argnames=("num_buckets", "bucket_size"))
def _partition_level(*arrs, num_buckets, bucket_size):
    """One median-split level: stable 2-key sort by (segment, split coord).

    ``arrs`` is ``(*coords, ids, pos, num_seg)`` — one column per point
    dimension (D-generic; D=3 reproduces the original x/y/z form bit for
    bit), then ids, pos, and the TRACED segment count so all levels share
    one compiled program. The split dimension is each segment's widest
    real-point extent; extents are computed shape-uniformly by reducing the
    static [B, S] fine-bucket grid first and then segment-min/maxing fine
    buckets into the level's coarser segments (segment boundaries always
    align with fine buckets because num_seg divides B). Values are
    identical to a direct [num_seg, seg]-shaped reduction, so the sort
    keys — and therefore the output, tie order included — are unchanged
    from the per-level-shape form this replaces.
    """
    cols, ids, pos, num_seg = arrs[:-3], arrs[-3], arrs[-2], arrs[-1]
    d = len(cols)
    n_tot = cols[0].shape[0]
    seg_id = jnp.arange(n_tot, dtype=jnp.int32) // (n_tot // num_seg)

    coords = jnp.stack(cols, axis=1).reshape(num_buckets, bucket_size, d)
    valid = coords[:, :, 0:1] < PAD_SENTINEL / 2
    lo_f = jnp.min(jnp.where(valid, coords, jnp.inf), axis=1)     # [B, D]
    hi_f = jnp.max(jnp.where(valid, coords, -jnp.inf), axis=1)
    seg_of_fine = (jnp.arange(num_buckets, dtype=jnp.int32)
                   // (num_buckets // num_seg))
    lo = jax.ops.segment_min(lo_f, seg_of_fine, num_segments=num_buckets)
    hi = jax.ops.segment_max(hi_f, seg_of_fine, num_segments=num_buckets)
    ext = hi - lo
    dim = jnp.argmax(jnp.where(jnp.isfinite(ext), ext, -jnp.inf),
                     axis=1).astype(jnp.int32)                    # [B]
    # broadcast, not jnp.repeat: segments are equal-size, and repeat's
    # general-case lowering builds a constant cumsum whose XLA constant
    # folding alone cost ~30 s at the 1M-point shape
    dim_e = jnp.broadcast_to(dim[seg_of_fine][:, None],
                             (num_buckets, bucket_size)).reshape(-1)
    key = cols[d - 1]
    for i in range(d - 2, -1, -1):   # nested select, widest-dim column wins
        key = jnp.where(dim_e == i, cols[i], key)

    out = lax.sort((seg_id, key) + tuple(cols) + (ids, pos),
                   num_keys=2, is_stable=True)
    return out[2:]


def partition_points(points: jnp.ndarray, point_ids: jnp.ndarray | None = None,
                     *, bucket_size: int = 512) -> BucketedPoints:
    """Partition ``f32[N,D]`` into ``B`` contiguous median-split buckets.

    Each of the ``log2 B`` levels is one stable multi-operand ``lax.sort``
    keyed by (segment-id, coordinate along the segment's widest extent) —
    segments are equal-size contiguous ranges, so segment ids are static
    ``iota // seg_size`` arrays and per-segment extents are plain reshaped
    min/max reductions. No scalar loops, fully jittable, static shapes.
    """
    points = jnp.asarray(points, jnp.float32)
    n = points.shape[0]
    num_buckets, bucket_size = choose_buckets(n, bucket_size)

    cols = partition_prep(points, point_ids, num_buckets=num_buckets,
                          bucket_size=bucket_size)
    num_levels = int(math.log2(num_buckets))
    # every level runs the SAME jitted program (_partition_level): segment
    # granularity rides in as a traced scalar, so XLA compiles ONE sort
    # pass and the remaining log2(B)-1 levels are cache hits — compiling a
    # distinct 7-operand million-row sort per level dominated the 1M-point
    # compile time otherwise. (The reuse only helps when this function runs
    # OUTSIDE an enclosing jit — inside one, each call inlines into the
    # trace; parallel/ring.py hoists the partition out for exactly that
    # reason.)
    for level in range(num_levels):
        cols = _partition_level(*cols, jnp.int32(1 << level),
                                num_buckets=num_buckets,
                                bucket_size=bucket_size)
    return partition_finalize(*cols, num_buckets=num_buckets,
                              bucket_size=bucket_size)


def partition_prep(points, point_ids, *, num_buckets, bucket_size):
    """Stage 1 of the split partition: pad + column-split to the D+2 sorted
    arrays ``(*coords, ids, pos)``. ``num_buckets``/``bucket_size`` come
    from ``choose_buckets``."""
    points = jnp.asarray(points, jnp.float32)
    n, d = points.shape
    if point_ids is None:
        point_ids = jnp.arange(n, dtype=jnp.int32)
    point_ids = jnp.asarray(point_ids, jnp.int32)
    pad = num_buckets * bucket_size - n

    cols = tuple(
        jnp.concatenate([points[:, i],
                         jnp.full((pad,), PAD_SENTINEL, jnp.float32)])
        for i in range(d))
    ids = jnp.concatenate([point_ids, jnp.full((pad,), -1, jnp.int32)])
    pos = jnp.concatenate([jnp.arange(n, dtype=jnp.int32),
                           jnp.full((pad,), -1, jnp.int32)])
    return cols + (ids, pos)


def partition_finalize(*arrs, num_buckets, bucket_size):
    """Stage 3: reshape the fully-sorted columns into buckets + AABBs."""
    cols, ids, pos = arrs[:-2], arrs[-2], arrs[-1]
    pts = jnp.stack(cols, axis=1).reshape(num_buckets, bucket_size,
                                          len(cols))
    ids = ids.reshape(num_buckets, bucket_size)
    pos = pos.reshape(num_buckets, bucket_size)

    valid = pts[:, :, 0:1] < PAD_SENTINEL / 2
    lower = jnp.min(jnp.where(valid, pts, jnp.inf), axis=1)
    upper = jnp.max(jnp.where(valid, pts, -jnp.inf), axis=1)
    return BucketedPoints(pts, ids, lower, upper, pos)


def bucket_box_dist2(q_lower, q_upper, p_lower, p_upper) -> jnp.ndarray:
    """Squared min box-to-box distance matrix f32[Bq, Bp].

    Same per-component formula as the reference's ``computeDistance``
    (prePartitionedDataVariant.cu:150-155), kept *squared* so pruning
    compares against squared heap radii without a sqrt. Empty buckets
    (inverted inf bounds) produce +inf — always prunable.
    """
    diff = jnp.maximum(0.0, jnp.maximum(q_lower[:, None, :] - p_upper[None, :, :],
                                        p_lower[None, :, :] - q_upper[:, None, :]))
    d2 = jnp.sum(diff * diff, axis=-1)
    return jnp.where(jnp.isnan(d2), jnp.inf, d2)


def nearest_first_order(q_lower, q_upper, p_lower, p_upper):
    """Per query bucket, point buckets in ascending box-distance order.

    Returns ``(sorted_d2 f32[Bq, Bp], order i32[Bq, Bp])`` — the shared
    visit schedule of the XLA and Pallas tiled engines (the traversal's
    "close child first" rule made global; stable sort fixes tie order
    identically in both twins).
    """
    box_d2 = bucket_box_dist2(q_lower, q_upper, p_lower, p_upper)
    iota = jnp.broadcast_to(
        jnp.arange(box_d2.shape[1], dtype=jnp.int32)[None, :], box_d2.shape)
    return lax.sort((box_d2, iota), num_keys=1, dimension=1, is_stable=True)


def coarsen_buckets(q: BucketedPoints, group: int) -> BucketedPoints:
    """Merge ``group`` adjacent buckets into one — the SAME arrays reshaped.

    The median-split hierarchy is nested: the fine partition's buckets
    [g*group, (g+1)*group) are exactly one coarser level's segment, so
    their concatenation is spatially contiguous and the union of their
    AABBs is tight. This gives the tiled engines a point side with
    ``group``x wider tiles (DMA/fold efficiency) while the query side
    keeps fine buckets (a per-bucket prune radius maxed over ``group``x
    fewer queries — tighter, so fewer lanes visited). Zero data movement:
    ``pts``/``ids``/``pos`` are reshapes of ``q``'s buffers.

    Empty fine buckets carry (+inf, -inf) bounds; min/max keeps the union
    correct (an all-empty coarse bucket stays empty-marked).
    """
    if group == 1:
        return q
    b, s = q.ids.shape
    d = q.pts.shape[-1]
    assert b % group == 0, (b, group)
    bc = b // group
    return BucketedPoints(
        q.pts.reshape(bc, group * s, d),
        q.ids.reshape(bc, group * s),
        q.lower.reshape(bc, group, d).min(axis=1),
        q.upper.reshape(bc, group, d).max(axis=1),
        q.pos.reshape(bc, group * s))


def scatter_back(values: jnp.ndarray, pos: jnp.ndarray, n_out: int,
                 fill=0) -> jnp.ndarray:
    """Scatter bucket-order ``values`` (any [B, S, ...]) back to input-row
    order; bucket padding rows (pos == -1) are dropped, and input rows not
    covered by ``pos`` hold ``fill``."""
    flat_pos = pos.reshape(-1)
    # -1 padding must map out of range, not wrap NumPy-style to the last row
    flat_pos = jnp.where(flat_pos < 0, n_out, flat_pos)
    flat_val = values.reshape((flat_pos.shape[0],) + values.shape[2:])
    out = jnp.full((n_out,) + flat_val.shape[1:], fill, flat_val.dtype)
    return out.at[flat_pos].set(flat_val, mode="drop")
