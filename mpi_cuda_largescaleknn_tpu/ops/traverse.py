"""Stack-free kd-tree traversal kNN engine (vectorized).

TPU re-expression of ``cukd::stackFree::knn`` (the reference's innermost hot
path, called per query thread at unorderedDataVariant.cu:86 /
prePartitionedDataVariant.cu:89; algorithm per Wald, arXiv:2210.12859):
walk the implicit left-balanced tree with parent/child index arithmetic only —
no stack — visiting a node's point when first arriving from its parent,
descending to the close child first, entering the far child only when the
splitting plane is closer than the query's current k-th-candidate radius, and
otherwise ascending.

Vectorization model: on the GPU each query is one scalar thread; here ALL
queries advance one automaton step per ``lax.while_loop`` iteration, carrying
``(curr, prev)`` index vectors and the candidate rows. Queries finish at
different times (divergence); finished lanes idle at curr == -1 until the
global predicate drains. This is the honest mapping of a branchy traversal
onto a vector machine — it wins over ops/brute_force.py when N is large enough
that O(log N)-ish visited nodes beat O(N) dense work despite lockstep padding;
the engines are exchangeable and benchmarked against each other.
"""

from __future__ import annotations

import warnings

import jax
import jax.numpy as jnp

from mpi_cuda_largescaleknn_tpu.core.types import CandidateState
from mpi_cuda_largescaleknn_tpu.ops.build_tree import node_depth

# beyond this many tree points per shard, the lockstep automaton's
# divergence padding makes it pathologically slow vs the tiled engines
_TREE_WARN_N = 200_000


def _insert_sorted(row_d2, row_idx, d2, idx, do_insert):
    """Insert one candidate into each sorted-ascending row (strict-< beat of
    the current worst slot, mirroring the heap's cutoff semantics)."""
    k = row_d2.shape[-1]
    do_insert = do_insert & (d2 < row_d2[:, -1])
    pos = jnp.sum(row_d2 < d2[:, None], axis=1)  # insertion position per row
    cols = jnp.arange(k)[None, :]
    shifted_d2 = jnp.concatenate([row_d2[:, :1], row_d2[:, :-1]], axis=1)
    shifted_idx = jnp.concatenate([row_idx[:, :1], row_idx[:, :-1]], axis=1)
    new_d2 = jnp.where(cols < pos[:, None], row_d2,
                       jnp.where(cols == pos[:, None], d2[:, None], shifted_d2))
    new_idx = jnp.where(cols < pos[:, None], row_idx,
                        jnp.where(cols == pos[:, None], idx[:, None], shifted_idx))
    keep = ~do_insert[:, None]
    return (jnp.where(keep, row_d2, new_d2),
            jnp.where(keep, row_idx, new_idx))


def knn_update_tree(state: CandidateState, queries: jnp.ndarray,
                    tree: jnp.ndarray, tree_ids: jnp.ndarray | None = None,
                    **_unused_tiling) -> CandidateState:
    """Fold every tree point into the candidate state via stack-free traversal.

    Drop-in alternative to ``knn_update_bruteforce`` (same contract as one
    reference ``runQuery`` launch). ``tree`` must be in implicit left-balanced
    layout (ops/build_tree.py).
    """
    n = tree.shape[0]
    if n == 0:
        return state
    if n > _TREE_WARN_N:
        warnings.warn(
            f"engine 'tree' with {n} points per shard: the lockstep "
            "traversal automaton degrades badly at this size (divergence "
            "padding) — use engine 'tiled' / 'pallas_tiled' / 'auto'",
            RuntimeWarning, stacklevel=2)
    if tree_ids is None:
        tree_ids = jnp.arange(n, dtype=jnp.int32)
    queries = jnp.asarray(queries, jnp.float32)
    num_q = queries.shape[0]

    def cond(carry):
        curr, _prev, _d2, _idx = carry
        return jnp.any(curr >= 0)

    def body(carry):
        curr, prev, hd2, hidx = carry
        active = curr >= 0
        safe = jnp.clip(curr, 0, n - 1)
        node_pt = tree[safe]          # gather f32[Q,3]
        node_id = tree_ids[safe]
        parent = jnp.where(curr > 0, (curr - 1) // 2, -1)

        from_parent = prev < curr
        visit = active & from_parent
        dx = queries[:, 0] - node_pt[:, 0]
        dy = queries[:, 1] - node_pt[:, 1]
        dz = queries[:, 2] - node_pt[:, 2]
        d2 = (dx * dx + dy * dy) + dz * dz
        hd2, hidx = _insert_sorted(hd2, hidx, d2, node_id, visit)

        dim = node_depth(safe) % 3
        qd = jnp.take_along_axis(queries, dim[:, None], axis=1)[:, 0]
        sd = qd - jnp.take_along_axis(node_pt, dim[:, None], axis=1)[:, 0]
        go_right = sd >= 0
        close = 2 * curr + 1 + go_right.astype(jnp.int32)
        far = 2 * curr + 2 - go_right.astype(jnp.int32)
        # enter the far child only if the splitting plane is closer than the
        # current k-th candidate AND the child exists; nonexistent children
        # are skipped outright (no wasted lockstep bounce steps)
        after_close = jnp.where((sd * sd < hd2[:, -1]) & (far < n), far, parent)
        nxt = jnp.where(from_parent,
                        jnp.where(close < n, close, after_close),
                        jnp.where(prev == close, after_close, parent))
        new_prev = jnp.where(active, curr, prev)
        new_curr = jnp.where(active, nxt, curr)
        return new_curr, new_prev, hd2, hidx

    # derive loop state from an input so it inherits the caller's
    # device-varying type under shard_map (a fresh constant would not)
    zero = state.idx[:, 0] * 0
    curr0 = zero
    prev0 = zero - 1
    curr, prev, hd2, hidx = jax.lax.while_loop(
        cond, body, (curr0, prev0, state.dist2, state.idx))
    return CandidateState(hd2, hidx)
