from mpi_cuda_largescaleknn_tpu.ops.candidates import (  # noqa: F401
    current_worst_radius,
    extract_final_result,
    init_candidates,
    merge_candidates,
)
from mpi_cuda_largescaleknn_tpu.ops.brute_force import (  # noqa: F401
    knn_update_bruteforce,
    pairwise_dist2,
)
from mpi_cuda_largescaleknn_tpu.ops.build_tree import build_tree  # noqa: F401
from mpi_cuda_largescaleknn_tpu.ops.traverse import knn_update_tree  # noqa: F401
