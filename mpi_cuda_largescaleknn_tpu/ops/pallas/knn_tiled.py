"""Bucketed nearest-first traversal as ONE Pallas kernel (engine ``pallas``
inside the tiled data path).

This is the TPU-native ``cukd::stackFree::knn`` (the reference's innermost hot
loop, unorderedDataVariant.cu:86): where the GPU walks one implicit-tree node
per scalar thread, pruning subtrees beyond the query's current k-th candidate,
this kernel walks one *point bucket* per step for a whole query bucket,
pruning buckets beyond the bucket's worst k-th candidate — the identical
nearest-first, radius-pruned search at tile granularity (see ops/tiled.py for
the algorithmic argument; this kernel is its fused form).

vs. the XLA twin (``ops.tiled.knn_update_tiled``), which lock-steps ALL query
buckets through one global visit counter and materializes every [S, T]
distance tile + a width-2k sort per visit, here:

- each query bucket advances its own ``lax.while_loop`` and exits as soon as
  *its* next-nearest unvisited bucket is beyond *its* radius (the GPU's
  per-thread early exit, recovered);
- the candidate rows live in VMEM for the bucket's whole traversal — HBM sees
  them once;
- point buckets are fetched from HBM with double-buffered async DMA keyed by
  the precomputed visit order, so the next bucket streams in while the
  current one is scored (the comm/compute overlap the reference forgoes,
  unorderedDataVariant.cu:204 — here at the memory level);
- the visit order and box distances are scalar-prefetched to SMEM, steering
  the DMAs without touching the vector core.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax import lax

from mpi_cuda_largescaleknn_tpu.core.types import PAD_SENTINEL, CandidateState
from mpi_cuda_largescaleknn_tpu.ops.distance import accumulate_sq, split_bf16
from mpi_cuda_largescaleknn_tpu.ops.pallas import tpu_compiler_params
from mpi_cuda_largescaleknn_tpu.ops.pallas.knn_bf import (
    default_fold_segments,
    fold_tile_into_candidates,
)
from mpi_cuda_largescaleknn_tpu.ops.partition import (
    BucketedPoints,
    nearest_first_order,
)
from mpi_cuda_largescaleknn_tpu.utils.compat import shape_dtype_struct


def _kernel(order_ref, boxd2_ref,            # SMEM: [1, 1, Bp] i32 / f32
            sskip_ref,                       # SMEM: [1, 1, 1] i32 skip-self
            q_ref, qid_ref,                  # VMEM: [1, S, 3] / [1, S, 1]
            in_d2_ref, in_idx_ref,           # VMEM: [S, k]
            p_hbm,                           # ANY (HBM): [Bp, 4, T]
            out_d2_ref, out_idx_ref,         # VMEM: [S, k]
            vis_ref,                         # SMEM: [1,1,2] i32 [visits,
                                             #        fold passes]
            p_buf, sem_p,                    # scratch: [2,rows,V*T], (2,V)
            *, visit_batch, self_group,
            fold_segments, score_mxu=False):
    num_pb = p_hbm.shape[0]
    t_p = p_hbm.shape[2]
    v_b = visit_batch
    num_chunks = (num_pb + v_b - 1) // v_b
    kk = in_d2_ref.shape[-1]
    q = q_ref[0]                             # [S, D]
    dim = q.shape[-1]
    # [S, 1] column layout so the bool mask never needs a minor-dim
    # insertion (Mosaic supports those only for 32-bit types)
    qvalid = qid_ref[0] >= 0                 # [S, 1]

    # Visits are processed V at a time: the chunk's buckets are DMAed into
    # adjacent lane windows of one [4, V*T] buffer, the distance broadcast
    # covers all of them in one [S, V*T] tile, and ONE fold merges the whole
    # chunk — amortizing the while-loop step, the DMA waits, and the fold's
    # extract-min passes over V buckets instead of paying them per bucket
    # (the per-visit form measured 85M pair-evals/s on a v5e: pure overhead).
    def chunk_copies(slot, c):
        # one descriptor per bucket; start and wait must describe the SAME
        # copies, so both go through this single generator. Point ids do
        # NOT ride along: the fold records lane positions and the wrapper
        # maps them to ids through the visit order after the kernel
        for v in range(v_b):                 # static unroll
            s_idx = jnp.minimum(c * v_b + v, num_pb - 1)
            visit = order_ref[0, 0, s_idx]
            yield pltpu.make_async_copy(
                p_hbm.at[visit], p_buf.at[slot, :, pl.ds(v * t_p, t_p)],
                sem_p.at[slot, v])

    def start_chunk(slot, c):
        for cp in chunk_copies(slot, c):
            cp.start()

    def wait_chunk(slot, c):
        for cp in chunk_copies(slot, c):
            cp.wait()

    def worst2(cd2):
        # static slice, NOT cd2[:, -1]: integer indexing lowers to
        # dynamic_slice, which Mosaic's TPU lowering rejects
        cd2_kth = lax.slice_in_dim(cd2, kk - 1, kk, axis=1)   # [S, 1]
        return jnp.max(jnp.where(qvalid, cd2_kth, -jnp.inf))

    start_chunk(0, 0)
    lane = lax.broadcasted_iota(jnp.int32, (1, v_b * t_p), 1)
    # read once at kernel scope: program_id inside the while body does not
    # lower under the CPU interpreter's HLO path. The own resident bucket
    # is b // self_group (coarsened point side, ops/partition.py)
    b_own = pl.program_id(0) // self_group
    sskip = sskip_ref[0, 0, 0] != 0

    def cond(carry):
        c, cd2, _cidx, _nv, _np = carry
        # nearest-first order is ascending in box distance, so if even the
        # chunk's FIRST bucket is beyond every query's radius, all later
        # buckets are too. & does not short-circuit in traced code: clamp
        # the index so the evaluation at c == num_chunks stays in bounds.
        first = jnp.minimum(c * v_b, num_pb - 1)
        return (c < num_chunks) & (boxd2_ref[0, 0, first] < worst2(cd2))

    def body(carry):
        c, cd2, cidx, nvis, npass = carry
        slot = lax.rem(c, 2)

        @pl.when(c + 1 < num_chunks)
        def _():
            start_chunk(lax.rem(c + 1, 2), c + 1)

        wait_chunk(slot, c)
        p = p_buf[slot]                       # [rows, V*T]; row D is ||p||^2
        if score_mxu:
            # matmul-form score (TPU-KNN): qn2 + pn2 - 2 q.p — the cross
            # term rides the MXU as the bf16x3 split (hi.hi + hi.lo +
            # lo.hi, three dot_generals with f32 accumulation, ~16
            # mantissa bits — one-pass bf16 measurably drops true top-k
            # members, ops/distance.py mxu_scores); both norms ride exact
            # f32 (||p||^2 was stowed in row D of the resident layout by
            # the wrapper). Approximate scores only SELECT candidates
            # here — the wrapper rescores every adopted entry with the
            # exact elementwise form after the kernel, against WIDENED
            # candidate rows (kk = rescore width)
            qn2 = None
            for i in range(dim):              # static unroll over D
                qi = q[:, i:i + 1]
                qn2 = qi * qi if qn2 is None else qn2 + qi * qi
            pc = p[0:dim, :]
            qh, ql = split_bf16(q)
            ph, plo = split_bf16(pc)
            dn = (((1,), (0,)), ((), ()))
            cross = (lax.dot_general(qh, ph, dn,
                                     preferred_element_type=jnp.float32)
                     + lax.dot_general(qh, plo, dn,
                                       preferred_element_type=jnp.float32)
                     + lax.dot_general(ql, ph, dn,
                                       preferred_element_type=jnp.float32))
            d2 = qn2 + p[dim:dim + 1, :] - 2.0 * cross     # [S, V*T]
        else:
            # exact elementwise, fixed left-to-right order, every square
            # carried through the opaque-1.0 contraction guard so the
            # kernel's bits match the XLA scorer's in every context
            # (ops/distance.py accumulate_sq; `one` derives from runtime
            # query data because Mosaic has no optimization_barrier)
            one = q[0, 0] * 0.0 + 1.0
            d2 = None
            for i in range(dim):
                di = q[:, i:i + 1] - p[i:i + 1, :]
                d2 = accumulate_sq(d2, di, one)
        # per-VISIT pruning inside the chunk (the per-node prune of
        # cukd::stackFree::knn, unorderedDataVariant.cu:86, recovered at
        # bucket granularity): a bucket whose box distance is at or beyond
        # the chunk-entry worst radius cannot be adopted by ANY query row
        # (point dist >= box dist >= every row's k-th), so its lanes go to
        # +inf. The distance broadcast still covers them — what this buys
        # is fewer fold extract-min passes (masked lanes never improve a
        # row) and a visits count at true per-bucket granularity. The same
        # mask drops the query bucket's OWN bucket when the heap was
        # pre-filled by warm_start_self (sskip nonzero): re-folding it
        # would adopt every self point twice.
        worst_c = worst2(cd2)
        s_idxs = [jnp.minimum(c * v_b + v, num_pb - 1) for v in range(v_b)]
        keep_v = [(boxd2_ref[0, 0, si] < worst_c)
                  & ~((order_ref[0, 0, si] == b_own) & sskip)
                  for si in s_idxs]           # static unroll, SMEM scalars
        # the last chunk may be padded with duplicates of bucket num_pb-1:
        # folding a point twice would corrupt the candidate list, so those
        # lanes are masked unconditionally (strict-< never adopts +inf).
        # The per-bucket mask rides as an f32 penalty row (+inf on dropped
        # buckets) rather than a bool vector: f32 full/concat/add are the
        # op classes this kernel already Mosaic-compiled in round 4;
        # broadcast bool vectors are not
        n_valid = (jnp.minimum(num_pb - c * v_b, v_b)) * t_p
        penalty = jnp.concatenate(
            [jnp.full((1, t_p), jnp.where(kv, 0.0, jnp.inf), jnp.float32)
             for kv in keep_v], axis=1)
        d2 = jnp.where(lane < n_valid, d2 + penalty, jnp.inf)
        # lane positions are global over the visit schedule: chunk c's lane
        # 0 sits at visit slot c*V, so pos // T = visit slot, pos % T = lane
        cd2, cidx, dp = fold_tile_into_candidates(d2, c * (v_b * t_p),
                                                  cd2, cidx,
                                                  with_passes=True,
                                                  segments=fold_segments)
        nvis = nvis + sum((kv & (c * v_b + v < num_pb)).astype(jnp.int32)
                          for v, kv in enumerate(keep_v))
        return c + 1, cd2, cidx, nvis, npass + dp

    c_exit, cd2, cidx, nvis, npass = lax.while_loop(
        cond, body, (jnp.int32(0), in_d2_ref[:], in_idx_ref[:],
                     jnp.int32(0), jnp.int32(0)))

    # a prefetch for chunk c_exit is in flight whenever the loop stopped
    # short of the end (started initially for c=0 or by the body for c+1);
    # drain it so no DMA outlives the kernel
    @pl.when(c_exit < num_chunks)
    def _():
        wait_chunk(lax.rem(c_exit, 2), c_exit)

    out_d2_ref[:] = cd2
    out_idx_ref[:] = cidx
    # buckets this query bucket actually scored (per-visit precision:
    # chunk-tail buckets beyond the entry radius and pad duplicates are
    # masked before the fold and excluded here) + tile-scan passes its
    # folds ran (each pass sweeps one whole [S, V*T] chunk and adopts up
    # to fold_segments candidates — the k-scaling cost center, see
    # fold_tile_into_candidates)
    vis_ref[0, 0, 0] = nvis
    vis_ref[0, 0, 1] = npass


def _vmem_limit(s_q: int, t_p: int, visit_batch: int, k: int) -> int:
    """Scoped-VMEM ceiling for the kernel's actual footprint.

    Dominant terms: the [S, V*T] f32 distance tile (plus its jnp.where
    twins — budget 3x), the double-buffered [2, 4, V*T] f32 chunk scratch,
    and the [S, k] x4 candidate rows. Everything else (query block, SMEM
    schedules) is noise. Keep the 16MB default whenever it suffices;
    otherwise pad the computed need by 2x for Mosaic's
    spills/temporaries, capped at 100MB (v5e physical VMEM is 128MiB).
    """
    lanes = visit_batch * t_p
    need = (3 * s_q * lanes * 4        # distance tile + masked copies
            + 2 * 4 * lanes * 4        # double-buffered chunk scratch
            + 4 * s_q * k * 4)         # candidate rows in/out
    default = 16 * 1024 * 1024
    if need <= default // 2:           # 2x headroom inside the default
        return default
    return min(max(2 * need, default), 100 * 1024 * 1024)


@functools.partial(jax.jit, static_argnames=("interpret", "visit_batch",
                                             "self_group", "fold_segments",
                                             "score_mxu"))
def _run(order, boxd2, sskip, q_pts, q_ids, in_d2, in_idx, p_t, *,
         interpret, visit_batch, self_group, fold_segments,
         score_mxu=False):
    num_qb, s_q, dim = q_pts.shape
    num_pb, _, t_p = p_t.shape
    k = in_d2.shape[-1]
    grid = (num_qb,)
    out_d2, out_idx, visits = pl.pallas_call(
        functools.partial(_kernel, visit_batch=visit_batch,
                          self_group=self_group,
                          fold_segments=fold_segments,
                          score_mxu=score_mxu),
        grid=grid,
        in_specs=[
            # Mosaic requires the LAST TWO block dims to be sublane/lane
            # aligned or equal to the array dims; a middle singleton makes
            # per-bucket rows of the SMEM schedule arrays legal blocks
            pl.BlockSpec((1, 1, num_pb), lambda b: (b, 0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, num_pb), lambda b: (b, 0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, 1), lambda b: (0, 0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, s_q, dim), lambda b: (b, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, s_q, 1), lambda b: (b, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((s_q, k), lambda b: (b, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((s_q, k), lambda b: (b, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=(
            pl.BlockSpec((s_q, k), lambda b: (b, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((s_q, k), lambda b: (b, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, 2), lambda b: (b, 0, 0),
                         memory_space=pltpu.SMEM),
        ),
        out_shape=(
            # under shard_map the outputs vary over the same mesh axes as the
            # candidate state; outside, vma is empty and this is a no-op
            # (utils/compat.py drops the typing on jax pins without it)
            shape_dtype_struct((num_qb * s_q, k), jnp.float32, like=in_d2),
            shape_dtype_struct((num_qb * s_q, k), jnp.int32, like=in_idx),
            shape_dtype_struct((num_qb, 1, 2), jnp.int32, like=in_idx),
        ),
        scratch_shapes=[
            pltpu.VMEM((2, p_t.shape[1], visit_batch * t_p), jnp.float32),
            pltpu.SemaphoreType.DMA((2, visit_batch)),
        ],
        compiler_params=tpu_compiler_params(pltpu,
            dimension_semantics=("arbitrary",),
            # the [S, V*T] distance tile + double-buffered chunk scratch put
            # ~19MB on the VMEM stack at the 1M config, beyond the 16MB
            # default scoped limit — raise the ceiling (v5e has 128MiB
            # physical VMEM) ONLY when the computed footprint needs it, so
            # small shapes and non-v5e parts keep the default guardrail
            vmem_limit_bytes=_vmem_limit(s_q, t_p, visit_batch, k)),
        interpret=interpret,
    )(order, boxd2, sskip, q_pts, q_ids, in_d2, in_idx, p_t)
    return out_d2, out_idx, visits


def knn_update_tiled_pallas(state: CandidateState, q: BucketedPoints,
                            p: BucketedPoints, *,
                            interpret: bool | None = None,
                            with_stats: bool | str = False,
                            visit_batch: int | None = None,
                            skip_self=None, self_group: int = 1,
                            canonical_ties: bool = False,
                            score_dtype: str = "f32",
                            point_norms2=None):
    """Drop-in Pallas twin of ``ops.tiled.knn_update_tiled`` (same contract:
    state rows in ``q``'s bucket order; folds every real point of ``p`` in;
    ``with_stats`` additionally returns the i32 count of [S, T] tiles
    scored — here the sum over query buckets of buckets each visited, since
    every bucket advances independently instead of lock-stepping;
    ``with_stats="full"`` returns ``(out, visits, fold_passes)`` where
    fold_passes is the summed TILE-SCAN count of the fold loops (each scan
    adopts up to ``fold_segments`` candidates — compare runs only at equal
    segment settings) — the k-scaling cost the warm start and the
    multi-extract fold exist to cap, for on-chip diagnosis
    (tools/tpu_probe);
    ``skip_self``/``self_group`` as in the twin: nonzero masks point bucket
    b // self_group out of query bucket b's traversal for warm-started
    self-joins).

    ``score_dtype="bf16"``: the in-kernel distance tile becomes the
    matmul-form MXU score (one bf16 dot_general per chunk, f32
    accumulation, ||p||^2 stowed in the resident layout's spare sublane
    row) and the candidate rows are WIDENED to ``rescore_width(k)`` slots —
    the kernel's fold then keeps the top-W survivors per row BY APPROX
    SCORE, and this wrapper rescores every adopted entry with the exact
    elementwise f32 form before sorting the rows back down to k. Emitted
    distances are therefore always exact; the kept SET matches the f32
    kernel whenever the true top-k sits inside the survivor window (the
    same guarantee as the XLA twin's bf16 mode — docs/TUNING.md "Distance
    kernel"). In-kernel pruning compares against the widened row's LAST
    slot, which is conservative (never prunes a bucket the f32 kernel
    would have visited). ``point_norms2`` optionally carries precomputed
    f32[Bp, T] squared norms (the serving engine computes them once at
    index upload).

    ``canonical_ties``: re-sort the finished candidate rows by the
    (dist2, idx) total order — the serving engine's multi-bucket tie
    discipline (ops/tiled.py). NOTE the twin difference: the XLA twin's
    canonical mode also makes the kept SET at the k-boundary canonical (its
    fold adopts ties by id and its visit predicate is non-strict); this
    kernel's in-VMEM fold keeps strict-< adoption, so at an exact
    equal-distance k-boundary straddling point buckets the kept ids can
    still follow visit order. Distances are exact either way; only
    duplicate-point id choices at that razor's edge differ (docs/TUNING.md
    "Query locality").

    Precondition: ``p.ids`` and ``state.idx`` entries must be ``>= -1``
    (true of everything this package produces — real ids are ``>= 0``, the
    pad sentinel is ``-1``). Values ``<= -2`` would alias the fold's
    lane-position encoding and decode to unrelated ids
    (fold_tile_into_candidates)."""
    from mpi_cuda_largescaleknn_tpu.ops.distance import (
        mxu_min_dim,
        rescore_width,
        validate_score_dtype,
    )

    validate_score_dtype(score_dtype)
    use_mxu = (score_dtype == "bf16"
               and q.pts.shape[-1] >= mxu_min_dim())
    if interpret is None:
        from mpi_cuda_largescaleknn_tpu.ops.pallas import is_tpu_backend
        interpret = not is_tpu_backend()
    num_qb, s_q = q.ids.shape
    dim = q.pts.shape[-1]
    k = state.dist2.shape[-1]

    sorted_d2, order = nearest_first_order(q.lower, q.upper,
                                           p.lower, p.upper)  # [Bq, Bp] x2

    # Mosaic DMA-slices p_hbm per bucket, so the sliced dims must match its
    # VMEM tiling: the coordinate dim rides the sublane axis (D rows, then
    # one ||p||^2 row, padded up to a multiple of 4) and the bucket dim
    # rides the lane axis (tiled in 128s — pad with the same PAD_SENTINEL/-1
    # rows partition_points uses; their distances overflow to +inf and are
    # never adopted by the fold)
    p3 = jnp.swapaxes(p.pts, 1, 2)            # [Bp, D, T]
    lane_pad = (-p3.shape[2]) % 128
    if lane_pad:
        p3 = jnp.pad(p3, ((0, 0), (0, 0), (0, lane_pad)),
                     constant_values=PAD_SENTINEL)
    # row D carries the exact f32 ||p||^2 per lane for the MXU score (the
    # previously unused tiling-pad row). Computed AFTER lane padding (or
    # +inf-padded when precomputed) so pad lanes overflow to +inf and can
    # never win a survivor slot. The f32 kernel never reads the row, so
    # the default mode keeps the old PAD_SENTINEL fill instead of paying
    # the O(Bp*T*D) norm compute on every call
    if not use_mxu:
        pn2 = jnp.full((p3.shape[0], p3.shape[2]), PAD_SENTINEL,
                       jnp.float32)
    elif point_norms2 is not None:
        pn2 = jnp.asarray(point_norms2, jnp.float32)
        if lane_pad:
            pn2 = jnp.pad(pn2, ((0, 0), (0, lane_pad)),
                          constant_values=jnp.inf)
    else:
        pn2 = None
        for i in range(dim):
            ri = p3[:, i, :]
            pn2 = ri * ri if pn2 is None else pn2 + ri * ri
    row_pad = (-(dim + 1)) % 4
    parts = [p3, pn2[:, None, :]]
    if row_pad:
        parts.append(jnp.full((p3.shape[0], row_pad, p3.shape[2]),
                              PAD_SENTINEL, jnp.float32))
    p_t = jnp.concatenate(parts, axis=1)      # [Bp, rows, T_pad]
    # id table for the post-kernel position decode (ids never enter the
    # kernel — see fold_tile_into_candidates); pad lanes decode to -1 but
    # are never adopted anyway (their coords are PAD_SENTINEL -> +inf d2)
    pid = p.ids
    if lane_pad:
        pid = jnp.pad(pid, ((0, 0), (0, lane_pad)), constant_values=-1)

    assert state.dist2.shape == (num_qb * s_q, k), (state.dist2.shape,
                                                    (num_qb, s_q, k))
    # widened candidate rows under the MXU score: the kernel's fold keeps
    # rescore_width(k) survivors per row by approx score, rescored and
    # sliced back to k after the kernel. The +inf fill preserves the
    # max_radius cutoff semantics: original slots (<= r^2) always sort
    # ahead of any widened-slot candidate at or beyond the radius
    k_eff = k
    if use_mxu:
        k_eff = rescore_width(k, p_t.shape[0] * p_t.shape[2])
        if k_eff > k:
            rows = num_qb * s_q
            state = CandidateState(
                jnp.concatenate([state.dist2,
                                 jnp.full((rows, k_eff - k), jnp.inf,
                                          jnp.float32)], axis=1),
                jnp.concatenate([state.idx,
                                 jnp.full((rows, k_eff - k), -1,
                                          jnp.int32)], axis=1))
    if visit_batch is None:
        # enough lanes per chunk to amortize the loop step (~2048) without
        # blowing the VMEM budget on the [S, V*T] distance tile.
        # LSK_CHUNK_LANES overrides for on-chip tuning — read at TRACE time,
        # so it must be set before the first run of a process (tpu_tune runs
        # one fresh subprocess per cell); changing it mid-process is ignored
        # by the jit cache
        lanes = int(os.environ.get("LSK_CHUNK_LANES", 2048))
        visit_batch = max(1, lanes // p_t.shape[2])
    visit_batch = min(visit_batch, p_t.shape[0])
    # multi-extract fold segments: adoptions per chunk scale with k, tile
    # scans are the expensive part — at k>=32 extract one min per 128-lane
    # segment per pass (fold_tile_into_candidates). LSK_FOLD_SEGS
    # overrides (trace-time, like LSK_CHUNK_LANES)
    lanes_total = visit_batch * p_t.shape[2]
    fold_segs = default_fold_segments(lanes_total, k_eff, env="LSK_FOLD_SEGS")
    ss = jnp.asarray(0 if skip_self is None else skip_self,
                     jnp.int32).reshape(1, 1, 1)
    out_d2, out_idx, visits = _run(order[:, None, :], sorted_d2[:, None, :],
                                   ss, q.pts, q.ids[:, :, None],
                                   state.dist2, state.idx, p_t,
                                   interpret=interpret,
                                   visit_batch=visit_batch,
                                   self_group=self_group,
                                   fold_segments=fold_segs,
                                   score_mxu=use_mxu)
    # decode encoded lane positions (<= -2) through the per-query-bucket
    # visit order: pos // T names the visit slot, pos % T the lane within
    # the visited bucket. Entries carried in from prior rounds / warm
    # starts are real ids (>= -1) and pass through untouched.
    t_pad = p_t.shape[2]
    enc = out_idx.reshape(num_qb, s_q * k_eff)
    pos = jnp.clip(-2 - enc, 0, p_t.shape[0] * t_pad - 1)
    flat_pos = jnp.take_along_axis(order, pos // t_pad, axis=1) * t_pad \
        + pos % t_pad
    ids_new = jnp.take(pid.reshape(-1), flat_pos, axis=0)
    out_idx = jnp.where(enc <= -2, ids_new, enc).reshape(out_idx.shape)
    if use_mxu:
        # exact f32 rescore of every entry the fold adopted by approx
        # score: gather the survivor coordinates back through the same
        # position decode and recompute the elementwise distance (the f32
        # kernel's expression tree), then sort the widened rows and slice
        # back to k. Entries carried in from prior rounds kept their exact
        # distances inside the kernel and pass through unchanged.
        from mpi_cuda_largescaleknn_tpu.ops.distance import opaque_one

        pflat = jnp.swapaxes(p_t[:, :dim, :], 1, 2).reshape(-1, dim)
        pg = jnp.take(pflat, flat_pos, axis=0).reshape(num_qb, s_q,
                                                       k_eff, dim)
        one = opaque_one(q.pts)
        acc = None
        for i in range(dim):
            acc = accumulate_sq(acc, q.pts[:, :, None, i] - pg[..., i], one)
        d2_new = jnp.where(enc <= -2, acc.reshape(num_qb, s_q * k_eff),
                           out_d2.reshape(num_qb, s_q * k_eff))
        d2r = d2_new.reshape(num_qb * s_q, k_eff)
        idr = out_idx.reshape(num_qb * s_q, k_eff)
        # values changed, so re-sort before slicing: stable 1-key keeps
        # the fold's arrival order among exact ties (the kernel's
        # documented boundary discipline); canonical mode uses the
        # (dist2, idx) total order like the XLA twin
        d2r, idr = lax.sort((d2r, idr),
                            num_keys=2 if canonical_ties else 1,
                            dimension=1, is_stable=True)
        out_d2, out_idx = d2r[:, :k], idr[:, :k]
    elif canonical_ties:
        # one [rows, k] two-key sort per call (not per visit): rows come
        # back ascending (dist2, idx) like the XLA twin's canonical mode
        out_d2, out_idx = lax.sort((out_d2, out_idx), num_keys=2,
                                   dimension=1, is_stable=True)
    out = CandidateState(out_d2, out_idx)
    if with_stats == "full":
        return (out, jnp.sum(visits[:, :, 0]).astype(jnp.int32),
                jnp.sum(visits[:, :, 1]).astype(jnp.int32))
    if with_stats:
        return out, jnp.sum(visits[:, :, 0]).astype(jnp.int32)
    return out
