"""Fused brute-force kNN Pallas kernel (flat engine ``pallas``).

Same contract as ``ops.brute_force.knn_update_bruteforce`` (one reference
``runQuery`` launch, unorderedDataVariant.cu:199-203): fold every point of the
resident shard into each query's persistent top-k candidate row. The XLA twin
materializes each [S, T] distance tile and merges it through a width-2k
``lax.sort``; here the distance tile, the threshold test, and the merge are one
kernel, and the candidate rows stay in VMEM across *all* point tiles of a
query tile (grid revisiting), touching HBM once per query tile.

Merge algorithm (exact, heap-free): per while-loop iteration every query row
extracts the minimum of its remaining distance row; rows whose minimum beats
their current k-th candidate insert it into their sorted candidate row
(strict-``<`` entry, ties keep existing entries first — FlexHeapCandidateList
semantics, ops/candidates.py) and mask that lane to +inf. The loop ends when
no row can improve — for a random point stream the expected iteration count
per tile decays as ~k/tiles_seen, so the merge costs a few [S, T] passes
total instead of a sort per tile.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mpi_cuda_largescaleknn_tpu.core.types import PAD_SENTINEL, CandidateState
from mpi_cuda_largescaleknn_tpu.ops.distance import accumulate_sq
from mpi_cuda_largescaleknn_tpu.ops.pallas import tpu_compiler_params
from mpi_cuda_largescaleknn_tpu.utils.compat import shape_dtype_struct
from mpi_cuda_largescaleknn_tpu.utils.math import cdiv


def default_fold_segments(lanes: int, k: int, cap: int = 16,
                          env: str | None = None) -> int:
    """Segment count for the multi-extract fold: one per 128-lane granule
    up to ``cap`` at k>=32 (the fold handles uneven granule counts by
    widening leading segments — no divisibility constraint), 1 below
    (the per-segment [S,k] inserts outweigh saved scans at small k).
    ``env`` names an environment variable that overrides when set
    (clamped to the granule count)."""
    granules = max(1, lanes // 128)
    if env:
        import os
        try:
            req = int(os.environ.get(env, "") or 0)
        except ValueError:
            req = 0                 # a bad sweep value must tune, not crash
        if req > 0:
            return max(1, min(req, granules))
    return max(1, min(granules, cap)) if k >= 32 else 1


def _segment_bounds(t: int, segments: int) -> list[int]:
    """Static slice boundaries for ``segments`` fold segments over ``t``
    lanes, each a multiple of 128 when ``t`` is (leading segments absorb
    the remainder granules); arbitrary (non-128) ``t`` falls back to
    equal widths and requires divisibility."""
    nseg = max(1, min(segments, t))
    if t % 128 == 0:
        g = t // 128
        nseg = min(nseg, g)
        base, extra = divmod(g, nseg)
        widths = [128 * (base + (1 if i < extra else 0))
                  for i in range(nseg)]
    else:
        assert t % nseg == 0, (t, nseg)
        widths = [t // nseg] * nseg
    bounds = [0]
    for w in widths:
        bounds.append(bounds[-1] + w)
    return bounds


def fold_tile_into_candidates(d2, lane_base, cand_d2, cand_idx,
                              with_passes: bool = False,
                              segments: int = 1):
    """Fold a distance tile ``f32[S, T]`` into sorted candidate rows.

    ``lane_base``: i32 scalar (traced or python int) — the global lane
    position of the tile's lane 0. Adopted entries are stored as ENCODED
    LANE POSITIONS ``-2 - (lane_base + lane)`` (distinct from real ids
    ``>= 0`` and the ``-1`` init sentinel, so they coexist with entries
    from prior rounds / warm starts in the same row); the caller maps
    positions back to point ids outside the kernel (`decode` helpers in
    the wrappers). Point ids never enter the kernel at all: an id row
    would have to be broadcast ``[1, T] -> [S, T]`` in i32, which Mosaic's
    TPU lowering crashes on at some geometries (v5e, S=64), while the lane
    index falls out of the extract-min bookkeeping for free.

    Returns updated (cand_d2, cand_idx), both [S, k]. Pure jnp — usable
    inside any kernel (or interpreted for tests). With ``with_passes``
    additionally returns the i32 number of tile-scan passes the loop ran —
    the k-scaling cost center (each pass sweeps the whole tile; a cold row
    pays up to ~k passes at segments=1, a warm-started row 1-3 — see
    ops/tiled.py warm_start_self).

    ``segments`` (static): each pass extracts the minimum of EACH lane
    segment (128-granule-aligned; leading segments absorb any remainder)
    and inserts up to ``segments`` candidates per row, so the pass count
    drops by up to that factor — the lever that makes k=100 affordable
    (adoptions per chunk scale with k; tile scans are the expensive part,
    the [S, k] inserts are cheap). The final content is IDENTICAL to
    segments=1: inserting into a sorted row is order-independent for the
    kept set, and segment order equals lane order, so strict-< boundary
    ties resolve to the same (lowest-lane) winner the global extract-min
    picks.
    """
    s, t = d2.shape
    k = cand_d2.shape[1]
    bounds = _segment_bounds(t, segments)
    nseg = len(bounds) - 1
    cols = jax.lax.broadcasted_iota(jnp.int32, (s, k), 1)

    def kth(cd2):
        # static slice, NOT cd2[:, -1]: integer indexing lowers to
        # dynamic_slice, which Mosaic's TPU lowering rejects
        return jax.lax.slice_in_dim(cd2, k - 1, k, axis=1)      # [S, 1]

    def insert(cd2, cidx, m, mid, improved):
        # sorted insert: after any equal entries (stable, existing first);
        # right-shift by one (the shifted col 0 is never selected: col > pos
        # is impossible at col 0)
        pos = jnp.sum((cd2 <= m[:, None]).astype(jnp.int32), axis=1)
        roll_d2 = jnp.concatenate([cd2[:, :1], cd2[:, :-1]], axis=1)
        roll_idx = jnp.concatenate([cidx[:, :1], cidx[:, :-1]], axis=1)
        ins_d2 = jnp.where(cols < pos[:, None], cd2,
                           jnp.where(cols == pos[:, None], m[:, None],
                                     roll_d2))
        ins_idx = jnp.where(cols < pos[:, None], cidx,
                            jnp.where(cols == pos[:, None], mid[:, None],
                                      roll_idx))
        return (jnp.where(improved, ins_d2, cd2),
                jnp.where(improved, ins_idx, cidx))

    def cond(carry):
        return carry[0]

    def body(carry):
        _, d2, cd2, cidx, npass = carry
        blocks = []
        for sg in range(nseg):                        # static unroll
            lo, hi = bounds[sg], bounds[sg + 1]
            w = hi - lo
            blk = jax.lax.slice_in_dim(d2, lo, hi, axis=1)
            lane_w = jax.lax.broadcasted_iota(jnp.int32, (s, w), 1)
            m = jnp.min(blk, axis=1)                  # [S]
            improved = m[:, None] < kth(cd2)          # [S, 1]
            # first lane holding the segment minimum
            is_min = blk == m[:, None]
            ml = jnp.min(jnp.where(is_min, lane_w, w), axis=1)
            sel = is_min & (lane_w == ml[:, None])
            # encoded global lane position of the extracted lane
            mid = -2 - (lane_base + lo + ml)
            # consume the extracted lane
            blocks.append(jnp.where(sel & improved, jnp.inf, blk))
            cd2, cidx = insert(cd2, cidx, m, mid, improved)
        d2 = blocks[0] if nseg == 1 else jnp.concatenate(blocks, axis=1)
        go = jnp.any(jnp.min(d2, axis=1)[:, None] < kth(cd2))
        return go, d2, cd2, cidx, npass + 1

    go0 = jnp.any(jnp.min(d2, axis=1)[:, None] < kth(cand_d2))
    _, _, cand_d2, cand_idx, npass = jax.lax.while_loop(
        cond, body, (go0, d2, cand_d2, cand_idx, jnp.int32(0)))
    if with_passes:
        return cand_d2, cand_idx, npass
    return cand_d2, cand_idx


def _kernel(q_ref, pt_ref, in_d2_ref, in_idx_ref,
            out_d2_ref, out_idx_ref, *, point_tile, fold_segments):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        out_d2_ref[:] = in_d2_ref[:]
        out_idx_ref[:] = in_idx_ref[:]

    q = q_ref[:]                                   # [S, D]
    # left-to-right accumulate with the opaque-1.0 contraction guard
    # (ops/distance.py accumulate_sq) so kernel bits match the XLA scorer
    one = q[0, 0] * 0.0 + 1.0
    d2 = None                                      # [S, T]
    for i in range(q.shape[-1]):                   # static unroll over D
        di = q[:, i:i + 1] - pt_ref[i:i + 1, :]
        d2 = accumulate_sq(d2, di, one)

    cd2, cidx = fold_tile_into_candidates(d2, j * point_tile, out_d2_ref[:],
                                          out_idx_ref[:],
                                          segments=fold_segments)
    out_d2_ref[:] = cd2
    out_idx_ref[:] = cidx


@functools.partial(jax.jit, static_argnames=("query_tile", "point_tile",
                                             "interpret", "fold_segments"))
def _run(q_pad, p_t, in_d2, in_idx, *, query_tile, point_tile,
         interpret, fold_segments):
    nq, k = in_d2.shape
    dim = q_pad.shape[1]
    npts = p_t.shape[1]
    grid = (nq // query_tile, npts // point_tile)
    out_d2, out_idx = pl.pallas_call(
        functools.partial(_kernel, point_tile=point_tile,
                          fold_segments=fold_segments),
        grid=grid,
        in_specs=[
            pl.BlockSpec((query_tile, dim), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((dim, point_tile), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((query_tile, k), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((query_tile, k), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((query_tile, k), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((query_tile, k), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ),
        out_shape=(
            # under shard_map the outputs vary over the same mesh axes as the
            # candidate state; outside, vma is empty and this is a no-op
            # (utils/compat.py drops the typing on jax pins without it)
            shape_dtype_struct((nq, k), jnp.float32, like=in_d2),
            shape_dtype_struct((nq, k), jnp.int32, like=in_idx),
        ),
        compiler_params=tpu_compiler_params(pltpu,
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(q_pad, p_t, in_d2, in_idx)
    return out_d2, out_idx


def decode_positions(idx, ids_flat):
    """Map encoded lane positions (``<= -2``, fold_tile_into_candidates)
    back to point ids via the padded id table; real ids and the ``-1``
    sentinel pass through untouched. One XLA gather — runs outside the
    kernel."""
    pos = jnp.clip(-2 - idx, 0, ids_flat.shape[0] - 1)
    return jnp.where(idx <= -2, jnp.take(ids_flat, pos, axis=0), idx)


def _pad_rows(arr, target, fill):
    n = arr.shape[0]
    if n == target:
        return arr
    pad_shape = (target - n,) + arr.shape[1:]
    return jnp.concatenate([arr, jnp.full(pad_shape, fill, arr.dtype)], axis=0)


def knn_update_pallas(state: CandidateState, queries: jnp.ndarray,
                      points: jnp.ndarray, point_ids: jnp.ndarray | None = None,
                      *, query_tile: int = 256, point_tile: int = 2048,
                      interpret: bool | None = None) -> CandidateState:
    """Drop-in Pallas twin of ``knn_update_bruteforce``.

    ``interpret=None`` auto-selects interpreter mode off-TPU so the same tests
    run on the CPU fixture.

    Precondition: ``point_ids`` and ``state.idx`` entries must be ``>= -1``
    (true of everything this package produces — real ids are ``>= 0``, the
    pad sentinel is ``-1``). Values ``<= -2`` would alias the fold's
    lane-position encoding and decode to unrelated ids
    (fold_tile_into_candidates).
    """
    if interpret is None:
        from mpi_cuda_largescaleknn_tpu.ops.pallas import is_tpu_backend
        interpret = not is_tpu_backend()
    num_q, k = state.dist2.shape
    num_p = points.shape[0]
    if num_p == 0:
        return state
    if point_ids is None:
        point_ids = jnp.arange(num_p, dtype=jnp.int32)

    # clamp to the problem size, then round UP to Mosaic-lowerable block
    # shapes (sublane multiple of 8, lane multiple of 128 for f32) — small
    # or odd N otherwise compiles in interpret mode but fails on real TPUs
    qt = cdiv(min(query_tile, max(8, num_q)), 8) * 8
    pt = cdiv(min(point_tile, max(128, num_p)), 128) * 128
    nq_pad = cdiv(num_q, qt) * qt
    np_pad = cdiv(num_p, pt) * pt

    q_pad = _pad_rows(jnp.asarray(queries, jnp.float32), nq_pad, PAD_SENTINEL)
    p_pad = _pad_rows(jnp.asarray(points, jnp.float32), np_pad, PAD_SENTINEL)
    ids_flat = _pad_rows(jnp.asarray(point_ids, jnp.int32), np_pad, -1)
    in_d2 = _pad_rows(state.dist2, nq_pad, jnp.inf)
    in_idx = _pad_rows(state.idx, nq_pad, -1)

    # computed OUTSIDE the jit and passed static, so an env change
    # retraces instead of silently reusing the old segment count (the
    # traversal kernel does the same — docs/TUNING.md)
    segs = default_fold_segments(pt, k, env="LSK_FOLD_SEGS")
    out_d2, out_idx = _run(q_pad, p_pad.T, in_d2, in_idx,
                           query_tile=qt, point_tile=pt, interpret=interpret,
                           fold_segments=segs)
    # entries the kernel adopted are encoded lane positions into the padded
    # point array; map them to ids here (ids never enter the kernel)
    out_idx = decode_positions(out_idx, ids_flat)
    return CandidateState(out_d2[:num_q], out_idx[:num_q])
