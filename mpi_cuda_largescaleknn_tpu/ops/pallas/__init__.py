"""Pallas TPU kernels for the hot query path.

Each kernel has a pure-XLA twin in ops/ with identical semantics; the Pallas
versions fuse distance evaluation with the top-k merge so the candidate state
stays resident in VMEM across point tiles instead of round-tripping to HBM
through an ``lax.sort`` per tile.
"""

from __future__ import annotations

import jax


def tpu_compiler_params(pltpu, **kwargs):
    """Build Mosaic compiler params across jax pins: current jax names the
    class ``CompilerParams``, older pins ``TPUCompilerParams``. One more
    drift bridge in the utils/compat.py spirit — call sites stay clean."""
    cls = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams")
    return cls(**kwargs)


def is_tpu_backend() -> bool:
    """True when the default backend is real TPU hardware — including the
    ``axon`` PJRT tunnel, whose platform name is not ``tpu`` but whose
    devices are TPU chips (Pallas kernels compile via Mosaic on it)."""
    try:
        if jax.default_backend() == "tpu":
            return True
        dev = jax.devices()[0]
        return "TPU" in getattr(dev, "device_kind", "")
    except Exception:
        return False
