"""Binary point-file input.

File formats are byte-compatible with the reference:

- ``.float3`` input: raw little-endian f32 triples, no header
  (``readFilePortion<float3>``, unorderedDataVariant.cu:41-63).
- file-of-filenames: one path per line (``readListOfFileNames``,
  prePartitionedDataVariant.cu:114-126). The reference drops the last line
  when the file lacks a trailing newline (SURVEY.md appendix) — that is a
  latent bug, not a contract; we read every non-empty line.

A native C++ fast path (pread, parallel slabs) is used when available — see
io/native.py; the numpy fallback is always correct.
"""

from __future__ import annotations

import os

import numpy as np

_RECORD_BYTES = 12  # one float3


def read_file_portion(path: str, rank: int, size: int):
    """Read shard ``rank`` of ``size``'s contiguous slab of a ``.float3`` file.

    Slab bounds are ``numData*rank/size .. numData*(rank+1)/size`` — the exact
    integer arithmetic of the reference (unorderedDataVariant.cu:55-57), so
    global output ordering matches byte-for-byte.

    Returns (points f32[n,3], begin, num_total).
    """
    num_bytes = os.path.getsize(path)
    num_data = num_bytes // _RECORD_BYTES
    begin = num_data * rank // size
    end = num_data * (rank + 1) // size
    from mpi_cuda_largescaleknn_tpu.io import native

    if native.available():
        # a native read that RUNS and fails (short read, IO error) raises —
        # silently re-reading with numpy would mask real corruption; numpy
        # is the fallback only when the library cannot be built at all
        pts = native.native_read_slab(path, begin, end - begin)
    else:
        with open(path, "rb") as f:
            f.seek(begin * _RECORD_BYTES)
            pts = np.fromfile(f, dtype=np.float32, count=(end - begin) * 3)
        pts = pts.reshape(-1, 3)
    return pts, begin, num_data


def read_points(path: str) -> np.ndarray:
    """Whole-file read (the prepartitioned variant's per-rank
    ``readFilePortion(..., 0, 1)``, prePartitionedDataVariant.cu:228-229).

    ``.npy`` inputs are accepted for D-generic point sets (the ``.float3``
    raw format is inherently 3-component): any f32-coercible [N, D] array
    serves — the matmul-form scorer is what makes high D affordable."""
    if path.endswith(".npy"):
        pts = np.asarray(np.load(path), np.float32)
        if pts.ndim != 2 or pts.shape[1] < 1:
            raise ValueError(f"{path}: expected an [N, D] array, got "
                             f"shape {list(pts.shape)}")
        return pts
    pts, _, _ = read_file_portion(path, 0, 1)
    return pts


def read_list_of_file_names(path: str) -> list[str]:
    with open(path) as f:
        return [line.strip() for line in f if line.strip()]
