"""ctypes binding to the native IO library (io/native_io.cpp).

Compiled on first use with g++ (cached next to the source); every entry point
has a pure-numpy fallback in io/reader.py / io/writer.py, so a missing
toolchain only costs speed, never correctness.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(__file__)
_SRC = os.path.join(_HERE, "native_io.cpp")
_LIB_PATH = os.path.join(_HERE, "_native_io.so")
_lock = threading.Lock()
_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    with _lock:
        if _lib is not None:
            return _lib
        if (not os.path.exists(_LIB_PATH)
                or os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC)):
            # compile to a private temp path then atomically rename, so a
            # concurrent process can never dlopen a half-written library
            tmp = f"{_LIB_PATH}.{os.getpid()}.tmp"
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-pthread",
                 "-o", tmp, _SRC],
                check=True, capture_output=True)
            os.replace(tmp, _LIB_PATH)
        lib = ctypes.CDLL(_LIB_PATH)
        lib.lsk_read_at.restype = ctypes.c_int64
        lib.lsk_read_at.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                    ctypes.c_int64, ctypes.c_void_p,
                                    ctypes.c_int32]
        lib.lsk_write_at.restype = ctypes.c_int64
        lib.lsk_write_at.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                     ctypes.c_int64, ctypes.c_void_p]
        lib.lsk_create_sized.restype = ctypes.c_int64
        lib.lsk_create_sized.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        lib.lsk_file_size.restype = ctypes.c_int64
        lib.lsk_file_size.argtypes = [ctypes.c_char_p]
        lib.lsk_partition.restype = ctypes.c_int64
        lib.lsk_partition.argtypes = [ctypes.c_char_p, ctypes.c_int32,
                                      ctypes.c_char_p, ctypes.c_int32,
                                      ctypes.c_void_p]
        _lib = lib
    return _lib


def available() -> bool:
    """True when the native library can be compiled/loaded on this machine.

    Distinguishes "no toolchain" (callers may fall back to numpy) from a
    native call that ran and FAILED (callers must surface that error, not
    silently retry in-memory)."""
    try:
        _load()
        return True
    except Exception:
        return False


def native_read_slab(path: str, begin_record: int, num_records: int,
                     num_threads: int = 8) -> np.ndarray:
    """Read ``num_records`` float3 records starting at ``begin_record``."""
    lib = _load()
    out = np.empty((num_records, 3), np.float32)
    nbytes = num_records * 12
    got = lib.lsk_read_at(path.encode(), begin_record * 12, nbytes,
                          out.ctypes.data_as(ctypes.c_void_p), num_threads)
    if got != nbytes:
        raise IOError(f"native read of {path} returned {got} != {nbytes}")
    return out


def native_create_sized(path: str, size_bytes: int) -> None:
    """Create/truncate ``path`` at exactly ``size_bytes`` — run once before
    concurrent ``native_write_at`` writers so a pre-existing longer file
    cannot leave stale trailing bytes."""
    lib = _load()
    if lib.lsk_create_sized(path.encode(), size_bytes) != 0:
        raise IOError(f"native create of {path} ({size_bytes} bytes) failed")


def native_write_at(path: str, offset_bytes: int, data: np.ndarray) -> None:
    """Positioned write (concurrent-writer-safe at disjoint offsets).

    When the target may already exist, pre-size it once with
    ``native_create_sized`` — this call alone never truncates."""
    lib = _load()
    data = np.ascontiguousarray(data)
    put = lib.lsk_write_at(path.encode(), offset_bytes, data.nbytes,
                           data.ctypes.data_as(ctypes.c_void_p))
    if put != data.nbytes:
        raise IOError(f"native write of {path} returned {put} != {data.nbytes}")


def native_partition(in_path: str, num_parts: int, out_prefix: str,
                     bits_per_dim: int = 7) -> np.ndarray:
    """Streaming Morton-order split of a .float3 file into ``num_parts``
    spatially-coherent ``<out_prefix>_%06d.float3`` files (3 sequential
    passes, O(8^bits) memory — any input size). Returns per-part counts."""
    lib = _load()
    counts = np.zeros(num_parts, np.int64)
    total = lib.lsk_partition(in_path.encode(), num_parts,
                              out_prefix.encode(), bits_per_dim,
                              counts.ctypes.data_as(ctypes.c_void_p))
    if total < 0:
        raise IOError(f"native partition of {in_path} failed")
    return counts
