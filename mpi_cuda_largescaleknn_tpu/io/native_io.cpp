// Native IO fast path for mpi_cuda_largescaleknn_tpu.
//
// TPU-native equivalent of the reference's host IO layer
// (readFilePortion / the output writers, unorderedDataVariant.cu:41-63,
// :229-237): positioned reads of a shard's contiguous slab of a raw
// .float3 file, and positioned writes that let every host write its slab
// of ONE output file concurrently — replacing the reference's R
// barrier-fenced sequential appends with offset pwrites.
//
// Built as a plain shared library (no pybind11); Python binds via ctypes
// (io/native.py). Multi-threaded chunked pread saturates page-cache /
// NVMe bandwidth for multi-GB inputs.

#include <cstdint>
#include <cstdio>
#include <fcntl.h>
#include <thread>
#include <unistd.h>
#include <vector>

extern "C" {

// Read `count` bytes at byte `offset` from `path` into `out`.
// Returns bytes read, or -1 on error.
int64_t lsk_read_at(const char *path, int64_t offset, int64_t count,
                    void *out, int32_t num_threads) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return -1;
  if (num_threads < 1) num_threads = 1;
  if (num_threads > 64) num_threads = 64;
  int64_t chunk = (count + num_threads - 1) / num_threads;
  std::vector<std::thread> workers;
  std::vector<int64_t> done(num_threads, 0);
  for (int t = 0; t < num_threads; t++) {
    workers.emplace_back([&, t]() {
      int64_t begin = t * chunk;
      int64_t end = begin + chunk < count ? begin + chunk : count;
      char *dst = (char *)out + begin;
      int64_t pos = begin;
      while (pos < end) {
        ssize_t got = pread(fd, dst + (pos - begin), end - pos, offset + pos);
        if (got <= 0) return;  // short read: done[t] stays short -> error
        pos += got;
      }
      done[t] = end - begin;
    });
  }
  int64_t total = 0;
  for (int t = 0; t < num_threads; t++) {
    workers[t].join();
    total += done[t];
  }
  close(fd);
  return total;
}

// Create (or truncate) `path` at exactly `size` bytes, so a set of
// concurrent lsk_write_at writers covering disjoint slabs produces exactly
// the intended file — without this step, rewriting an existing LONGER file
// would leave stale trailing bytes from the prior run. Call once, before
// the writers start. Returns 0, or -1 on error.
int64_t lsk_create_sized(const char *path, int64_t size) {
  int fd = open(path, O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return -1;
  if (ftruncate(fd, size) != 0) { close(fd); return -1; }
  close(fd);
  return 0;
}

// Write `count` bytes from `src` at byte `offset` of `path`, creating the
// file if needed (safe for concurrent writers at disjoint offsets — but the
// file must be pre-sized with lsk_create_sized first when it may already
// exist, since O_CREAT without O_TRUNC keeps stale trailing bytes).
// Returns bytes written, or -1 on error.
int64_t lsk_write_at(const char *path, int64_t offset, int64_t count,
                     const void *src) {
  int fd = open(path, O_WRONLY | O_CREAT, 0644);
  if (fd < 0) return -1;
  const char *p = (const char *)src;
  int64_t pos = 0;
  while (pos < count) {
    ssize_t put = pwrite(fd, p + pos, count - pos, offset + pos);
    if (put <= 0) { close(fd); return -1; }
    pos += put;
  }
  close(fd);
  return pos;
}

// File size in bytes, or -1.
int64_t lsk_file_size(const char *path) {
  FILE *f = fopen(path, "rb");
  if (!f) return -1;
  fseeko(f, 0, SEEK_END);
  int64_t n = ftello(f);
  fclose(f);
  return n;
}

// ---------------------------------------------------------------------------
// Streaming spatial partitioner.
//
// The reference's prepartitioned variant ASSUMES one spatially-coherent file
// per rank already exists (README.md:17-23) and ships no tool to produce
// them. This is that tool: split a raw .float3 file into `num_parts` files
// of near-equal size whose points are spatially coherent, by bucketing on a
// quantized Morton (Z-order) code and cutting the code space into
// equal-count ranges. Out-of-core: three sequential streaming passes
// (bounds, histogram, route), O(bins) memory, any input size.

static inline uint64_t expand_bits21(uint64_t v) {
  // spread the low 21 bits of v so there are 2 zero bits between each
  v &= 0x1fffff;
  v = (v | v << 32) & 0x1f00000000ffffULL;
  v = (v | v << 16) & 0x1f0000ff0000ffULL;
  v = (v | v << 8) & 0x100f00f00f00f00fULL;
  v = (v | v << 4) & 0x10c30c30c30c30c3ULL;
  v = (v | v << 2) & 0x1249249249249249ULL;
  return v;
}

static inline uint64_t morton3(float x, float y, float z, const float *lo,
                               const float *inv_ext, int bits) {
  uint64_t max_q = (1ULL << bits) - 1;
  uint64_t qx = (uint64_t)((x - lo[0]) * inv_ext[0] * (double)max_q);
  uint64_t qy = (uint64_t)((y - lo[1]) * inv_ext[1] * (double)max_q);
  uint64_t qz = (uint64_t)((z - lo[2]) * inv_ext[2] * (double)max_q);
  if (qx > max_q) qx = max_q;
  if (qy > max_q) qy = max_q;
  if (qz > max_q) qz = max_q;
  return (expand_bits21(qx) << 2) | (expand_bits21(qy) << 1) |
         expand_bits21(qz);
}

// Partition `in_path` (raw float3 records) into `num_parts` files named
// `<out_prefix>_%06d.float3`. `bits_per_dim` (<= 10 recommended) sets the
// histogram resolution: bins = 2^(3*bits). `out_counts` (size num_parts)
// receives per-part point counts. Returns total points, or -1 on error.
int64_t lsk_partition(const char *in_path, int32_t num_parts,
                      const char *out_prefix, int32_t bits_per_dim,
                      int64_t *out_counts) {
  if (num_parts < 1 || bits_per_dim < 1 || bits_per_dim > 10) return -1;
  int64_t fsize = lsk_file_size(in_path);
  if (fsize < 0 || fsize % 12 != 0) return -1;
  int64_t n = fsize / 12;

  const size_t CHUNK = 1 << 20;  // points per streaming chunk (12 MB)
  std::vector<float> buf(CHUNK * 3);

  // pass 1: bounds
  float lo[3] = {3.4e38f, 3.4e38f, 3.4e38f};
  float hi[3] = {-3.4e38f, -3.4e38f, -3.4e38f};
  {
    FILE *f = fopen(in_path, "rb");
    if (!f) return -1;
    int64_t seen = 0;
    while (seen < n) {
      size_t want = (size_t)((n - seen) < (int64_t)CHUNK ? (n - seen) : CHUNK);
      if (fread(buf.data(), 12, want, f) != want) { fclose(f); return -1; }
      for (size_t i = 0; i < want; i++)
        for (int d = 0; d < 3; d++) {
          float v = buf[i * 3 + d];
          if (v < lo[d]) lo[d] = v;
          if (v > hi[d]) hi[d] = v;
        }
      seen += want;
    }
    fclose(f);
  }
  float inv_ext[3];
  for (int d = 0; d < 3; d++) {
    float e = hi[d] - lo[d];
    inv_ext[d] = e > 0 ? 1.0f / e : 0.0f;
  }

  // pass 2: histogram over morton bins
  size_t bins = (size_t)1 << (3 * bits_per_dim);
  std::vector<int64_t> hist(bins, 0);
  {
    FILE *f = fopen(in_path, "rb");
    if (!f) return -1;
    int64_t seen = 0;
    while (seen < n) {
      size_t want = (size_t)((n - seen) < (int64_t)CHUNK ? (n - seen) : CHUNK);
      if (fread(buf.data(), 12, want, f) != want) { fclose(f); return -1; }
      for (size_t i = 0; i < want; i++)
        hist[morton3(buf[i * 3], buf[i * 3 + 1], buf[i * 3 + 2], lo, inv_ext,
                     bits_per_dim)]++;
      seen += want;
    }
    fclose(f);
  }

  // cut the code space into num_parts equal-count ranges:
  // part r gets bins [cut[r], cut[r+1]) with prefix(cut[r]) ~= n*r/parts
  std::vector<size_t> cut(num_parts + 1, bins);
  cut[0] = 0;
  {
    int64_t acc = 0;
    int32_t r = 1;
    for (size_t b = 0; b < bins && r < num_parts; b++) {
      acc += hist[b];
      while (r < num_parts && acc >= n * (int64_t)r / num_parts)
        cut[r++] = b + 1;
    }
  }
  std::vector<int32_t> bin_part(bins);
  for (int32_t r = 0; r < num_parts; r++)
    for (size_t b = cut[r]; b < cut[r + 1]; b++) bin_part[b] = r;

  // pass 3: route points to per-part buffered output files
  std::vector<FILE *> outs(num_parts, nullptr);
  auto close_all = [&]() {
    for (int32_t r = 0; r < num_parts; r++)
      if (outs[r]) fclose(outs[r]);
  };
  for (int32_t r = 0; r < num_parts; r++) {
    char name[4096];
    snprintf(name, sizeof name, "%s_%06d.float3", out_prefix, r);
    outs[r] = fopen(name, "wb");
    if (!outs[r]) {
      close_all();
      return -1;
    }
    out_counts[r] = 0;
  }
  std::vector<std::vector<float>> obuf(num_parts);
  const size_t FLUSH = 1 << 16;  // floats (~256 KB per part)
  auto flush_part = [&](int32_t r) {
    size_t nf = obuf[r].size();
    if (nf && fwrite(obuf[r].data(), 4, nf, outs[r]) != nf) return false;
    obuf[r].clear();
    return true;
  };
  int64_t total = 0;
  {
    FILE *f = fopen(in_path, "rb");
    if (!f) {
      close_all();
      return -1;
    }
    int64_t seen = 0;
    while (seen < n) {
      size_t want = (size_t)((n - seen) < (int64_t)CHUNK ? (n - seen) : CHUNK);
      if (fread(buf.data(), 12, want, f) != want) {
        fclose(f);
        close_all();
        return -1;
      }
      for (size_t i = 0; i < want; i++) {
        int32_t r = bin_part[morton3(buf[i * 3], buf[i * 3 + 1],
                                     buf[i * 3 + 2], lo, inv_ext,
                                     bits_per_dim)];
        obuf[r].insert(obuf[r].end(), &buf[i * 3], &buf[i * 3 + 3]);
        out_counts[r]++;
        if (obuf[r].size() >= FLUSH && !flush_part(r)) {
          fclose(f);
          close_all();
          return -1;  // short write (disk full): fail loudly, not silently
        }
      }
      seen += want;
      total += want;
    }
    fclose(f);
  }
  bool ok = true;
  for (int32_t r = 0; r < num_parts; r++) {
    if (!flush_part(r)) ok = false;
    if (fclose(outs[r]) != 0) ok = false;
    outs[r] = nullptr;
  }
  return ok ? total : -1;
}

}  // extern "C"
