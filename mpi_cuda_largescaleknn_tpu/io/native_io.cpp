// Native IO fast path for mpi_cuda_largescaleknn_tpu.
//
// TPU-native equivalent of the reference's host IO layer
// (readFilePortion / the output writers, unorderedDataVariant.cu:41-63,
// :229-237): positioned reads of a shard's contiguous slab of a raw
// .float3 file, and positioned writes that let every host write its slab
// of ONE output file concurrently — replacing the reference's R
// barrier-fenced sequential appends with offset pwrites.
//
// Built as a plain shared library (no pybind11); Python binds via ctypes
// (io/native.py). Multi-threaded chunked pread saturates page-cache /
// NVMe bandwidth for multi-GB inputs.

#include <cstdint>
#include <cstdio>
#include <fcntl.h>
#include <thread>
#include <unistd.h>
#include <vector>

extern "C" {

// Read `count` bytes at byte `offset` from `path` into `out`.
// Returns bytes read, or -1 on error.
int64_t lsk_read_at(const char *path, int64_t offset, int64_t count,
                    void *out, int32_t num_threads) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return -1;
  if (num_threads < 1) num_threads = 1;
  if (num_threads > 64) num_threads = 64;
  int64_t chunk = (count + num_threads - 1) / num_threads;
  std::vector<std::thread> workers;
  std::vector<int64_t> done(num_threads, 0);
  for (int t = 0; t < num_threads; t++) {
    workers.emplace_back([&, t]() {
      int64_t begin = t * chunk;
      int64_t end = begin + chunk < count ? begin + chunk : count;
      char *dst = (char *)out + begin;
      int64_t pos = begin;
      while (pos < end) {
        ssize_t got = pread(fd, dst + (pos - begin), end - pos, offset + pos);
        if (got <= 0) return;  // short read: done[t] stays short -> error
        pos += got;
      }
      done[t] = end - begin;
    });
  }
  int64_t total = 0;
  for (int t = 0; t < num_threads; t++) {
    workers[t].join();
    total += done[t];
  }
  close(fd);
  return total;
}

// Write `count` bytes from `src` at byte `offset` of `path`, creating the
// file if needed (safe for concurrent writers at disjoint offsets).
// Returns bytes written, or -1 on error.
int64_t lsk_write_at(const char *path, int64_t offset, int64_t count,
                     const void *src) {
  int fd = open(path, O_WRONLY | O_CREAT, 0644);
  if (fd < 0) return -1;
  const char *p = (const char *)src;
  int64_t pos = 0;
  while (pos < count) {
    ssize_t put = pwrite(fd, p + pos, count - pos, offset + pos);
    if (put <= 0) { close(fd); return -1; }
    pos += put;
  }
  close(fd);
  return pos;
}

// File size in bytes, or -1.
int64_t lsk_file_size(const char *path) {
  FILE *f = fopen(path, "rb");
  if (!f) return -1;
  fseeko(f, 0, SEEK_END);
  int64_t n = ftello(f);
  fclose(f);
  return n;
}

}  // extern "C"
