from mpi_cuda_largescaleknn_tpu.io.reader import (  # noqa: F401
    read_file_portion,
    read_list_of_file_names,
    read_points,
)
from mpi_cuda_largescaleknn_tpu.io.writer import (  # noqa: F401
    write_distances,
    write_rank_file,
)
