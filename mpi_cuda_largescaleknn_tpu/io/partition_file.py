"""Spatial pre-partitioning of a raw .float3 file.

The reference's prepartitioned variant requires one spatially-coherent file
per rank but ships no tool to produce them (README.md:17-23 just assumes
them). ``partition_float3_file`` is that tool: Morton (Z-order) bucketing
with equal-count cuts, matching the partitioning the reference's use case
implies. Native streaming C++ path (io/native_io.cpp, out-of-core, any input
size) with a numpy fallback implementing the identical rule (same float32
quantization, same cut positions), so the two paths produce byte-identical
outputs.
"""

from __future__ import annotations

import numpy as np

# one bit-dilation core for the whole repo (verified bit-identical to the
# C++ expandBits over the full 21-bit domain)
from mpi_cuda_largescaleknn_tpu.utils.math import _part1by2 as _expand_bits21


def morton_codes(pts: np.ndarray, lo: np.ndarray, inv_ext: np.ndarray,
                 bits: int) -> np.ndarray:
    """Quantized 3-D Morton codes — bit-identical to the C++ ``morton3``:
    float32 ``(p - lo) * inv_ext``, float64 scaling by ``2^bits - 1``,
    truncation, clamp.

    NOT interchangeable with ``utils/math.py morton_codes`` (the serving
    admission sort): that one puts x in the LOW interleave position,
    quantizes in float64 with below-box clamping, and maps sentinel rows
    to a pads-last max code; this one reproduces the C++ partitioner bit
    for bit (x HIGH, float32 arithmetic, truncate-and-clamp-above). Both
    share the ``_part1by2`` dilation core."""
    max_q = np.uint64((1 << bits) - 1)
    t = (pts.astype(np.float32) - lo.astype(np.float32)) \
        * inv_ext.astype(np.float32)                    # float32, like C++
    q = (t.astype(np.float64) * np.float64(max_q)).astype(np.uint64)
    q = np.minimum(q, max_q)
    return (_expand_bits21(q[:, 0]) << np.uint64(2)) \
        | (_expand_bits21(q[:, 1]) << np.uint64(1)) | _expand_bits21(q[:, 2])


def partition_float3_file_np(in_path: str, num_parts: int, out_prefix: str,
                             bits_per_dim: int = 7) -> np.ndarray:
    """Numpy twin of the native partitioner (in-memory; small files/tests).

    Matches the native path's edge behavior too: a file whose size is not a
    multiple of 12 bytes is rejected (the C++ checks fsize % 12), and an
    empty input yields empty part files with zero counts."""
    import os

    if os.path.getsize(in_path) % 12 != 0:
        raise IOError(f"{in_path} is not a whole number of float3 records")
    pts = np.fromfile(in_path, np.float32).reshape(-1, 3)
    n = len(pts)
    if n == 0:
        for pr in range(num_parts):
            pts.tofile(f"{out_prefix}_{pr:06d}.float3")
        return np.zeros(num_parts, np.int64)
    lo = pts.min(axis=0)
    ext = pts.max(axis=0) - lo                           # float32
    inv_ext = np.where(ext > 0, np.float32(1.0) / np.where(ext > 0, ext, 1),
                       np.float32(0.0)).astype(np.float32)
    codes = morton_codes(pts, lo, inv_ext, bits_per_dim)

    bins = 1 << (3 * bits_per_dim)
    prefix = np.cumsum(np.bincount(codes.astype(np.int64), minlength=bins))
    # cut[r] = (first bin whose inclusive prefix >= floor(n*r/parts)) + 1,
    # exactly the C++ while-loop
    cut = np.full(num_parts + 1, bins, np.int64)
    cut[0] = 0
    for r in range(1, num_parts):
        cut[r] = np.searchsorted(prefix, n * r // num_parts, side="left") + 1
    cut = np.maximum.accumulate(cut)
    part_of = np.searchsorted(cut[1:], codes, side="right")

    counts = np.zeros(num_parts, np.int64)
    for pr in range(num_parts):
        sel = pts[part_of == pr]
        sel.tofile(f"{out_prefix}_{pr:06d}.float3")
        counts[pr] = len(sel)
    return counts


def partition_float3_file(in_path: str, num_parts: int, out_prefix: str,
                          bits_per_dim: int = 7,
                          write_file_list: bool = True) -> np.ndarray:
    """Split ``in_path`` into ``num_parts`` spatially-coherent float3 files.

    Uses the native streaming path when the toolchain is available, numpy
    otherwise — but a native run that FAILS raises (falling back to the
    load-everything numpy path would mask the error and blow memory at
    exactly the out-of-core scale the native path exists for). Optionally
    writes ``<out_prefix>.txt`` listing the part files (the prepartitioned
    CLI's input format). Returns per-part counts.
    """
    if not 1 <= bits_per_dim <= 10:
        raise ValueError(f"bits_per_dim must be in [1, 10], got {bits_per_dim}")
    if num_parts < 1:
        raise ValueError(f"num_parts must be >= 1, got {num_parts}")
    from mpi_cuda_largescaleknn_tpu.io import native
    if native.available():
        counts = native.native_partition(in_path, num_parts, out_prefix,
                                         bits_per_dim)
    else:
        counts = partition_float3_file_np(in_path, num_parts, out_prefix,
                                          bits_per_dim)
    if write_file_list:
        with open(f"{out_prefix}.txt", "w") as f:
            for r in range(num_parts):
                f.write(f"{out_prefix}_{r:06d}.float3\n")
    return np.asarray(counts)
