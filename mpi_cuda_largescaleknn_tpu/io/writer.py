"""Binary distance-file output.

- unordered variant: ONE ``.float`` file holding every point's k-th-NN
  distance in global point order. The reference produces this with R
  barrier-fenced sequential appends, one rank at a time
  (unorderedDataVariant.cu:229-237); here the results are already gathered in
  rank order, so it's a single write (on a multi-host pod each host pwrites
  its slab at its byte offset — no serialization needed, see io/native.py).
- prepartitioned variant: one ``prefix_%06d.float`` file per shard
  (prePartitionedDataVariant.cu:380-385).
"""

from __future__ import annotations

import numpy as np


def write_distances(path: str, distances: np.ndarray) -> None:
    np.asarray(distances, np.float32).tofile(path)


def write_rank_file(prefix: str, rank: int, distances: np.ndarray) -> str:
    """Write one shard's results as ``<prefix>_%06d.float``."""
    path = f"{prefix}_{rank:06d}.float"
    np.asarray(distances, np.float32).tofile(path)
    return path


def write_indices(path: str, idx: np.ndarray) -> None:
    """Row-major i32[N, k] neighbor ids (-1 = fewer than k found)."""
    np.asarray(idx, np.int32).tofile(path)


def write_rank_indices(prefix: str, rank: int, idx: np.ndarray) -> str:
    path = f"{prefix}_{rank:06d}.int32"
    np.asarray(idx, np.int32).tofile(path)
    return path
