"""Binary distance-file output.

- unordered variant: ONE ``.float`` file holding every point's k-th-NN
  distance in global point order. The reference produces this with R
  barrier-fenced sequential appends, one rank at a time
  (unorderedDataVariant.cu:229-237); here the results are already gathered in
  rank order, so it's a single write (on a multi-host pod each host pwrites
  its slab at its byte offset — no serialization needed, see io/native.py).
- prepartitioned variant: one ``prefix_%06d.float`` file per shard
  (prePartitionedDataVariant.cu:380-385).
"""

from __future__ import annotations

import numpy as np


def write_distances(path: str, distances: np.ndarray) -> None:
    np.asarray(distances, np.float32).tofile(path)


def write_distances_slab(path: str, begin_record: int,
                         distances: np.ndarray, total_records: int,
                         presize: bool = False) -> None:
    """Multi-host output path: each host pwrites its slab of the ONE global
    ``.float`` file at its record offset — the reference's barrier-fenced
    rank-serialized append (unorderedDataVariant.cu:229-237) without the
    serialization. Exactly one writer (by convention host 0) must run with
    ``presize=True`` before the others write, so a stale longer file from a
    prior run cannot leave trailing bytes (io/native_io.cpp
    lsk_create_sized).
    """
    from mpi_cuda_largescaleknn_tpu.io import native

    data = np.ascontiguousarray(np.asarray(distances, np.float32))
    if native.available():
        if presize:
            native.native_create_sized(path, total_records * 4)
        native.native_write_at(path, begin_record * 4, data)
        return
    # numpy fallback (no toolchain): plain positioned writes
    import os
    if presize or not os.path.exists(path):
        with open(path, "wb") as f:
            f.truncate(total_records * 4)
    with open(path, "r+b") as f:
        f.seek(begin_record * 4)
        f.write(data.tobytes())


def write_rank_file(prefix: str, rank: int, distances: np.ndarray) -> str:
    """Write one shard's results as ``<prefix>_%06d.float``."""
    path = f"{prefix}_{rank:06d}.float"
    np.asarray(distances, np.float32).tofile(path)
    return path


def write_indices(path: str, idx: np.ndarray) -> None:
    """Row-major i32[N, k] neighbor ids (-1 = fewer than k found)."""
    np.asarray(idx, np.int32).tofile(path)


def write_rank_indices(prefix: str, rank: int, idx: np.ndarray) -> str:
    path = f"{prefix}_{rank:06d}.int32"
    np.asarray(idx, np.int32).tofile(path)
    return path
