"""mpi_cuda_largescaleknn_tpu — a TPU-native large-scale exact-kNN framework.

A brand-new JAX/XLA/Pallas framework with the capabilities of the reference
MPI+CUDA system (ingowald/MPI-CUDA-LargeScaleKNN): for a very large set of 3D
points — larger than one accelerator's memory — compute, for every point, the
distance to its k-th nearest neighbor.

Architecture (TPU-first, not a translation):

- ``ops.build_tree``   — left-balanced implicit kd-tree built with O(log N)
  ``lax.sort`` passes (the reference builds it with GPU sort kernels inside the
  ``cudaKDTree`` submodule, called at ``unorderedDataVariant.cu:161``).
- ``ops.candidates``   — persistent per-query top-k candidate state as SoA
  ``(f32[N,k] dist^2, i32[N,k] idx)`` arrays, with the same init/adopt/extract
  semantics as ``cukd::FlexHeapCandidateList`` (``unorderedDataVariant.cu:84-102``).
- ``ops.brute_force``  — exact blocked kNN update (VPU outer-difference form).
- ``ops.traverse``     — stack-free kd-tree traversal engine (vectorized).
- ``parallel.ring``    — the reference's MPI ring exchange
  (``unorderedDataVariant.cu:173-205``) re-expressed as ``lax.ppermute`` over a
  1-D ``jax.sharding.Mesh`` inside ``shard_map``: stationary queries + heaps,
  rotating tree shards (the ring-attention-shaped pattern).
- ``parallel.demand``  — the reference's bounds-pruned demand exchange with
  global early exit (``prePartitionedDataVariant.cu:304-357``) re-expressed as
  a ``lax.while_loop`` with per-device compute skipping and a ``pmax``-driven
  all-done predicate.
- ``io`` / ``cli``     — byte-compatible ``.float3`` input and ``.float``
  distance output, and the exact 5-flag CLI surface of the two reference
  binaries.
"""

__version__ = "0.1.0"

from mpi_cuda_largescaleknn_tpu.core.config import KnnConfig  # noqa: F401


def kth_neighbor_distances(points, k, *, max_radius=float("inf"),
                           num_shards: int = 0, engine: str = "auto",
                           return_neighbors: bool = False, **config_kwargs):
    """One-call API: distance from every point to its k-th nearest neighbor.

    The library form of the reference's CLI contract
    (``mpirun -n R ./cudaMpiKNN_unorderedData pts.float3 -o out.float -k K``):
    ``points`` is ``f32[N, 3]`` (numpy or jax); returns ``f32[N]`` in input
    order (``inf`` where fewer than k neighbors exist within ``max_radius``).
    With ``return_neighbors`` also returns ``i32[N, k]`` neighbor ids —
    something the reference computes but discards. ``num_shards=0`` uses
    every visible device; other ``KnnConfig`` fields pass through
    (``bucket_size``, ``query_chunk``, ``checkpoint_dir``, ...).
    """
    from mpi_cuda_largescaleknn_tpu.models.unordered import UnorderedKNN

    cfg = KnnConfig(k=k, max_radius=max_radius, engine=engine,
                    num_shards=num_shards, **config_kwargs)
    return UnorderedKNN(cfg).run(points, return_neighbors=return_neighbors)
