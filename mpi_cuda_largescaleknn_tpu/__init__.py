"""mpi_cuda_largescaleknn_tpu — a TPU-native large-scale exact-kNN framework.

A brand-new JAX/XLA/Pallas framework with the capabilities of the reference
MPI+CUDA system (ingowald/MPI-CUDA-LargeScaleKNN): for a very large set of 3D
points — larger than one accelerator's memory — compute, for every point, the
distance to its k-th nearest neighbor.

Architecture (TPU-first, not a translation):

- ``ops.build_tree``   — left-balanced implicit kd-tree built with O(log N)
  ``lax.sort`` passes (the reference builds it with GPU sort kernels inside the
  ``cudaKDTree`` submodule, called at ``unorderedDataVariant.cu:161``).
- ``ops.candidates``   — persistent per-query top-k candidate state as SoA
  ``(f32[N,k] dist^2, i32[N,k] idx)`` arrays, with the same init/adopt/extract
  semantics as ``cukd::FlexHeapCandidateList`` (``unorderedDataVariant.cu:84-102``).
- ``ops.brute_force``  — exact blocked kNN update (VPU outer-difference form).
- ``ops.traverse``     — stack-free kd-tree traversal engine (vectorized).
- ``parallel.ring``    — the reference's MPI ring exchange
  (``unorderedDataVariant.cu:173-205``) re-expressed as ``lax.ppermute`` over a
  1-D ``jax.sharding.Mesh`` inside ``shard_map``: stationary queries + heaps,
  rotating tree shards (the ring-attention-shaped pattern).
- ``parallel.demand``  — the reference's bounds-pruned demand exchange with
  global early exit (``prePartitionedDataVariant.cu:304-357``) re-expressed as
  a ``lax.while_loop`` with per-device compute skipping and a ``pmax``-driven
  all-done predicate.
- ``io`` / ``cli``     — byte-compatible ``.float3`` input and ``.float``
  distance output, and the exact 5-flag CLI surface of the two reference
  binaries.
"""

__version__ = "0.1.0"

from mpi_cuda_largescaleknn_tpu.core.config import KnnConfig  # noqa: F401
