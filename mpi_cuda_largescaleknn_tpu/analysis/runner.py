"""Pass orchestration: discover files, run AST passes, apply waivers.

The default scope is the package plus the serving tools — everything the
parity and threading contracts cover. Tests are out of scope (they may
use any clock/RNG they like), and so are the repo-root bench drivers
(batch budget tracking is not a serving path).
"""

from __future__ import annotations

import ast
import os

from mpi_cuda_largescaleknn_tpu.analysis.determinism import check_determinism
from mpi_cuda_largescaleknn_tpu.analysis.findings import Finding, Report
from mpi_cuda_largescaleknn_tpu.analysis.locks import (
    check_lock_discipline,
    collect_classes,
    lock_order_findings,
    resolve_inheritance,
)
from mpi_cuda_largescaleknn_tpu.analysis.waivers import (
    WaiverTable,
    parse_waivers,
)

#: analyzed roots, relative to the repo root
DEFAULT_ROOTS = ("mpi_cuda_largescaleknn_tpu", "tools")


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def discover_files(roots=DEFAULT_ROOTS, base: str | None = None) -> list[str]:
    base = base or repo_root()
    out = []
    for root in roots:
        full = os.path.join(base, root)
        if os.path.isfile(full) and full.endswith(".py"):
            out.append(full)
            continue
        if not os.path.isdir(full):
            # a missing root must fail loudly — os.walk would yield
            # nothing and the blocking gate would pass vacuously green
            raise FileNotFoundError(
                f"lskcheck: analyzed root does not exist: {full}")
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return sorted(out)


def analyze_source(source: str, path: str) -> tuple[
        list[Finding], list, WaiverTable]:
    """One file's determinism findings + collected classes (for the
    cross-file lock passes) + its waiver table. ``path`` is the label
    used in findings (repo-relative for real files)."""
    waivers = parse_waivers(source, path)
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return ([Finding("waiver", path, e.lineno or 1,
                         f"file does not parse: {e.msg}")],
                [], waivers)
    findings = list(waivers.errors)
    findings += check_determinism(tree, path)
    classes = collect_classes(tree, path)
    return findings, classes, waivers


def apply_waivers(findings: list[Finding],
                  tables: dict[str, WaiverTable]) -> None:
    for f in findings:
        if f.waived:
            continue
        table = tables.get(f.path)
        if table is None:
            continue
        reason = table.waiver_for(f.rule, f.line)
        if reason is not None:
            f.waived = True
            f.waiver_reason = reason


def run_files(paths: list[str], base: str | None = None) -> Report:
    """AST passes over ``paths``; finding paths are repo-relative."""
    base = base or repo_root()
    report = Report()
    all_classes = []
    tables: dict[str, WaiverTable] = {}
    for path in paths:
        rel = os.path.relpath(path, base)
        with open(path, encoding="utf-8") as fh:
            source = fh.read()
        findings, classes, waivers = analyze_source(source, rel)
        report.findings += findings
        all_classes += classes
        tables[rel] = waivers
        report.files_checked += 1
    resolve_inheritance(all_classes)
    report.findings += check_lock_discipline(all_classes, tables)
    order_findings, edges = lock_order_findings(all_classes)
    report.findings += order_findings
    report.lock_order_edges = edges
    apply_waivers(report.findings, tables)
    return report


def run_repo(roots=DEFAULT_ROOTS, base: str | None = None,
             aot: bool = True, aot_update: bool = False) -> Report:
    """The full gate: AST passes over the default scope, then (unless
    ``aot=False``) the AOT-contract diff against docs/aot_contract.json.
    ``aot_update`` rewrites the golden instead of diffing."""
    base = base or repo_root()
    report = run_files(discover_files(roots, base), base)
    if aot:
        from mpi_cuda_largescaleknn_tpu.analysis import aot as aot_mod

        golden = os.path.join(base, aot_mod.CONTRACT_RELPATH)
        contract = aot_mod.trace_contract()
        report.aot_programs = sum(
            len(cfg["programs"]) for cfg in contract["configs"])
        if aot_update:
            aot_mod.write_contract(contract, golden)
        else:
            report.findings += aot_mod.diff_contract(contract, golden)
    return report
