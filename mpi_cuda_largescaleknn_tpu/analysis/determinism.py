"""Determinism / parity rules over one module's AST.

Everything here defends the stack's bitwise-parity contract (PAPER.md:
exact kNN; TPU-KNN arXiv:2206.14286's fixed-shape numeric discipline):
results must be a pure function of (index bytes, query bytes, config),
never of wall-clock, RNG state, arrival order, or sort stability luck.

Rules (ids in findings.RULES):

- ``wallclock``       : ``time.time`` / ``time.time_ns`` /
                        ``datetime.now|utcnow|today`` calls. Elapsed-time
                        measurement belongs to ``perf_counter`` /
                        ``monotonic``; schedule state belongs to an
                        injectable clock (serve/health.py pattern) so
                        tests drive it without sleeps.
- ``rng-unseeded``    : module-level ``random.*`` calls (shared global
                        state), no-arg ``random.Random()`` /
                        ``np.random.default_rng()``, and the legacy
                        ``np.random.*`` global generator.
- ``float-eq``        : ``==`` / ``!=`` where an operand is
                        distance-like (name matches ``d2|dist|kth|
                        radius``) or a float literal. Exact bitwise tie
                        detection is sometimes the CONTRACT (the
                        canonical-ties fix) — those sites carry
                        ``# lsk: allow[float-eq]`` waivers, which is the
                        point: every float equality is auditable.
- ``sort-unstable``   : ``np.sort``/``np.argsort`` over distance-like
                        operands without ``kind='stable'``, and
                        ``lax.sort`` over distance-like operands without
                        ``is_stable=True`` unless it is a multi-key
                        ``(dist2, id)`` sort (``num_keys >= 2`` — a total
                        order needs no stability).
- ``dict-order-fold`` : ``for`` over ``.keys()``/``.values()`` inside a
                        fold/merge-named function — host folds must not
                        depend on dict insertion (= arrival) order.
- ``except-swallow``  : handler bodies that are only ``pass`` /
                        ``continue`` for broad exception classes. Errors
                        feed the ``*_errors`` counter pattern instead.
"""

from __future__ import annotations

import ast
import re

from mpi_cuda_largescaleknn_tpu.analysis.findings import Finding

_DIST_RE = re.compile(r"(^|_)(d2|dsq|dist\w*|kth\w*|radius\w*)($|_)",
                      re.IGNORECASE)
_FOLD_FN_RE = re.compile(r"(fold|merge|reduce|assemble|combine)",
                         re.IGNORECASE)

#: random-module functions that consume the SHARED global stream
_RANDOM_GLOBAL_FNS = {
    "random", "randint", "randrange", "uniform", "choice", "choices",
    "sample", "shuffle", "gauss", "normalvariate", "betavariate",
    "expovariate", "triangular", "vonmisesvariate", "paretovariate",
    "weibullvariate", "lognormvariate", "getrandbits", "seed",
}

#: legacy numpy global-RNG entry points (np.random.<fn>)
_NP_RANDOM_GLOBAL_FNS = {
    "rand", "randn", "randint", "random", "random_sample", "choice",
    "shuffle", "permutation", "normal", "uniform", "seed", "standard_normal",
    "exponential", "poisson", "beta", "gamma",
}


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted name of an expression ('' when not a name)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _leaf_name(node: ast.AST) -> str:
    """Last identifier of a Name/Attribute ('' otherwise)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _is_distance_like(node: ast.AST) -> bool:
    name = _leaf_name(node)
    return bool(name and _DIST_RE.search(name))


def _kw(call: ast.Call, name: str) -> ast.expr | None:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


class _DeterminismVisitor(ast.NodeVisitor):
    def __init__(self, path: str):
        self.path = path
        self.findings: list[Finding] = []
        self._fn_stack: list[str] = []

    def _emit(self, rule: str, node: ast.AST, msg: str) -> None:
        self.findings.append(Finding(rule, self.path, node.lineno, msg))

    # --------------------------------------------------------------- scopes

    def visit_FunctionDef(self, node):
        self._fn_stack.append(node.name)
        self.generic_visit(node)
        self._fn_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    # ---------------------------------------------------------------- calls

    def visit_Call(self, node: ast.Call):
        dotted = _dotted(node.func)
        # wallclock ---------------------------------------------------------
        if dotted in ("time.time", "time.time_ns"):
            self._emit("wallclock", node,
                       f"{dotted}() in a deterministic/serving path — use "
                       "time.perf_counter/monotonic for intervals or an "
                       "injectable clock (serve/health.py) for schedules")
        elif dotted.endswith((".now", ".utcnow", ".today")) and \
                ("datetime" in dotted or "date" in dotted.split(".")[0]):
            self._emit("wallclock", node,
                       f"{dotted}() wall-clock read — results must not "
                       "depend on the calendar")
        # rng ---------------------------------------------------------------
        if (isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "random"
                and node.func.attr in _RANDOM_GLOBAL_FNS):
            self._emit("rng-unseeded", node,
                       f"random.{node.func.attr}() uses the shared global "
                       "stream — construct random.Random(seed) per owner")
        if dotted == "random.Random" and not node.args and not node.keywords:
            self._emit("rng-unseeded", node,
                       "random.Random() without a seed is "
                       "os-entropy-seeded — pass an explicit seed")
        if dotted.endswith("random.default_rng") and not node.args \
                and not node.keywords:
            self._emit("rng-unseeded", node,
                       "np.random.default_rng() without a seed is "
                       "os-entropy-seeded — pass an explicit seed")
        if (isinstance(node.func, ast.Attribute)
                and _dotted(node.func.value) in ("np.random", "numpy.random")
                and node.func.attr in _NP_RANDOM_GLOBAL_FNS):
            self._emit("rng-unseeded", node,
                       f"np.random.{node.func.attr}() drives the legacy "
                       "GLOBAL numpy generator — use "
                       "np.random.default_rng(seed)")
        # sorts -------------------------------------------------------------
        self._check_sort(node, dotted)
        self.generic_visit(node)

    def _check_sort(self, node: ast.Call, dotted: str) -> None:
        leaf = dotted.rsplit(".", 1)[-1] if dotted else ""
        dist_args = any(_is_distance_like(a) for a in node.args) or any(
            kw.arg is None and _is_distance_like(kw.value)
            for kw in node.keywords)
        # tuple operands: lax.sort takes ((d2, idx), ...)
        for a in node.args:
            if isinstance(a, (ast.Tuple, ast.List)):
                dist_args = dist_args or any(_is_distance_like(e)
                                             for e in a.elts)
        if not dist_args:
            return
        if dotted.endswith(("np.argsort", "numpy.argsort")):
            # plain np.sort of VALUES is order-deterministic whatever the
            # algorithm; only argsort (ids ride along) is tie-sensitive
            kind = _kw(node, "kind")
            if not (isinstance(kind, ast.Constant)
                    and kind.value == "stable"):
                self._emit("sort-unstable", node,
                           f"{leaf}() over distance-like data without "
                           "kind='stable' — equal distances may reorder "
                           "their ids between numpy versions/backends")
        elif dotted.endswith("lax.sort"):
            nk = _kw(node, "num_keys")
            multi_key = (isinstance(nk, ast.Constant)
                         and isinstance(nk.value, int) and nk.value >= 2)
            stable = _kw(node, "is_stable")
            is_stable = (isinstance(stable, ast.Constant)
                         and stable.value is True)
            if not (multi_key or is_stable):
                self._emit("sort-unstable", node,
                           "lax.sort over distance-like data is UNSTABLE "
                           "by default — pass is_stable=True or sort the "
                           "(dist2, id) pair with num_keys=2")

    # ----------------------------------------------------------------- cmp

    def visit_Compare(self, node: ast.Compare):
        operands = [node.left] + list(node.comparators)
        eq_ops = [op for op in node.ops if isinstance(op, (ast.Eq, ast.NotEq))]
        if eq_ops:
            # comparisons against strings/None are config checks, not
            # numeric equality
            benign = any(isinstance(o, ast.Constant)
                         and (o.value is None or isinstance(o.value, str))
                         for o in operands)
            if not benign:
                if any(_is_distance_like(o) for o in operands):
                    self._emit("float-eq", node,
                               "float equality on a distance-like value — "
                               "bitwise tie checks must be deliberate "
                               "(waive with a reason) and everything else "
                               "should compare through the canonical "
                               "(dist2, id) order")
                elif any(isinstance(o, ast.Constant)
                         and isinstance(o.value, float)
                         for o in operands):
                    self._emit("float-eq", node,
                               "== / != against a float literal — exact "
                               "float equality is rarely what serving "
                               "code means")
        self.generic_visit(node)

    # ---------------------------------------------------------------- loops

    def visit_For(self, node: ast.For):
        in_fold = any(_FOLD_FN_RE.search(fn) for fn in self._fn_stack)
        if in_fold and isinstance(node.iter, ast.Call):
            fn = node.iter.func
            if isinstance(fn, ast.Attribute) and fn.attr in ("keys",
                                                             "values"):
                self._emit("dict-order-fold", node,
                           f"fold iterates .{fn.attr}() — dict order is "
                           "insertion (= arrival) order; fold over "
                           "sorted(...) or an index-ordered list so the "
                           "result cannot depend on who answered first")
        self.generic_visit(node)

    # --------------------------------------------------------------- except

    def visit_ExceptHandler(self, node: ast.ExceptHandler):
        broad = node.type is None or (
            isinstance(node.type, ast.Name)
            and node.type.id in ("Exception", "BaseException"))
        if broad and _is_silent_body(node.body):
            what = ("bare except:" if node.type is None
                    else f"except {node.type.id}:")
            self._emit("except-swallow", node,
                       f"{what} swallows the error silently — record it "
                       "(last_error + *_errors counter, the PR-8 pattern) "
                       "or narrow the exception type")
        self.generic_visit(node)


def _is_silent_body(body: list[ast.stmt]) -> bool:
    """True when the handler does nothing observable: only pass/continue
    (string-constant expressions count as comments)."""
    for stmt in body:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        if (isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Constant)):
            continue
        return False
    return True


def check_determinism(tree: ast.AST, path: str) -> list[Finding]:
    v = _DeterminismVisitor(path)
    v.visit(tree)
    return v.findings
