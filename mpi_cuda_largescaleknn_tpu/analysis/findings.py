"""Finding model + report serialization shared by every analysis pass.

A ``Finding`` is one rule violation at one source location. Passes emit
findings UNfiltered; the runner applies the waiver table afterwards so a
waived finding still appears in the machine-readable report (audit trail)
— it just stops gating the exit code.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

#: rule id -> one-line description; the registry the waiver grammar
#: validates against (an ``allow[...]`` naming an unknown rule is itself
#: a finding — typos must not silently waive nothing).
RULES = {
    "lock-guard": "guarded attribute accessed without holding its "
                  "declared lock",
    "lock-holds": "method declared `# lsk: holds[lock]` called without "
                  "the lock held",
    "lock-order": "lock acquisition-order cycle (potential deadlock "
                  "between threads)",
    "wallclock": "wall-clock time in a deterministic/serving path (use "
                 "time.monotonic/perf_counter or an injectable clock)",
    "rng-unseeded": "unseeded / globally-shared RNG in a deterministic "
                    "path (seed an instance: random.Random(seed) / "
                    "np.random.default_rng(seed))",
    "float-eq": "float == / != on a distance-like value (ties must go "
                "through the canonical (dist2, id) discipline)",
    "sort-unstable": "potentially unstable sort of distance-like data in "
                     "tie-sensitive code (use kind='stable' / "
                     "is_stable=True / a (dist2, id) 2-key sort)",
    "dict-order-fold": "fold iterates dict keys/values — arrival-order "
                       "iteration can change fold results; iterate a "
                       "canonically sorted view",
    "except-swallow": "exception silently swallowed (log it and count it "
                      "— extend the *_errors counter pattern)",
    "waiver": "malformed waiver comment (unknown rule or missing reason)",
    "aot-contract": "AOT shape-bucket program signature drifted from the "
                    "committed docs/aot_contract.json golden",
}


@dataclass
class Finding:
    rule: str
    path: str
    line: int
    message: str
    waived: bool = False
    waiver_reason: str | None = None

    def to_dict(self) -> dict:
        out = {"rule": self.rule, "path": self.path, "line": self.line,
               "message": self.message, "waived": self.waived}
        if self.waiver_reason:
            out["waiver_reason"] = self.waiver_reason
        return out

    def render(self) -> str:
        tag = " (waived)" if self.waived else ""
        return f"{self.path}:{self.line}: [{self.rule}]{tag} {self.message}"


@dataclass
class Report:
    """All findings of one run + enough metadata to gate CI on."""

    findings: list[Finding] = field(default_factory=list)
    files_checked: int = 0
    lock_order_edges: list[str] = field(default_factory=list)
    aot_programs: int = 0

    @property
    def unwaived(self) -> list[Finding]:
        return [f for f in self.findings if not f.waived]

    @property
    def ok(self) -> bool:
        return not self.unwaived

    def summary(self) -> dict:
        per_rule: dict[str, int] = {}
        for f in self.unwaived:
            per_rule[f.rule] = per_rule.get(f.rule, 0) + 1
        return {
            "ok": self.ok,
            "files_checked": self.files_checked,
            "findings": len(self.unwaived),
            "waived": sum(1 for f in self.findings if f.waived),
            "per_rule": dict(sorted(per_rule.items())),
            "aot_programs": self.aot_programs,
        }

    def to_dict(self) -> dict:
        return {
            "summary": self.summary(),
            "findings": [f.to_dict() for f in
                         sorted(self.findings,
                                key=lambda f: (f.path, f.line, f.rule))],
            "lock_order_edges": sorted(self.lock_order_edges),
        }

    def dump_json(self, path: str) -> None:
        with open(path, "w") as fh:
            json.dump(self.to_dict(), fh, indent=2, sort_keys=False)
            fh.write("\n")
