"""The ``# lsk:`` comment grammar: auditable waivers + method contracts.

Grammar (one directive per comment)::

    # lsk: allow[rule] reason text          waive `rule` findings here
    # lsk: allow[rule1,rule2] reason text   waive several rules at once
    # lsk: holds[_lock]                     def-line contract: callers
                                            must hold self._lock
    # lsk: holds[_lock,_cond]               several locks

Placement: trailing on the offending line, or alone on the line
immediately ABOVE it (the next physical line is then covered — the usual
home for waivers on statements that are already at the line-length
limit). A waiver must carry a non-empty reason; ``holds`` takes none (it
is a contract, not a suppression). A directive naming an unknown rule,
or an ``allow`` with no reason, is itself reported under the ``waiver``
rule — typos must not silently waive nothing.

Comments are read with ``tokenize`` so strings containing ``# lsk:`` can
never be mistaken for directives.
"""

from __future__ import annotations

import io
import re
import tokenize
from dataclasses import dataclass, field

from mpi_cuda_largescaleknn_tpu.analysis.findings import RULES, Finding

_DIRECTIVE_RE = re.compile(
    r"#\s*lsk:\s*(?P<kind>allow|holds)\[(?P<args>[^\]]*)\]\s*(?P<reason>.*)")


@dataclass
class WaiverTable:
    """Per-file directive index.

    ``allows``: line -> {rule: reason}; ``holds``: line -> [lock names]
    (the line of the ``def`` the contract is attached to).
    """

    allows: dict[int, dict[str, str]] = field(default_factory=dict)
    holds: dict[int, list[str]] = field(default_factory=dict)
    errors: list[Finding] = field(default_factory=list)
    #: rules waived per line that a pass actually matched — lets the
    #: runner flag unused waivers later if we ever want to (not a gate).
    used: set = field(default_factory=set)

    def waiver_for(self, rule: str, line: int) -> str | None:
        """Reason string if ``rule`` is waived at ``line``, else None."""
        reasons = self.allows.get(line)
        if reasons is not None and rule in reasons:
            self.used.add((line, rule))
            return reasons[rule]
        return None

    def holds_for(self, def_line: int) -> list[str]:
        return self.holds.get(def_line, [])


def _comment_tokens(source: str):
    """(line, column, comment_text) for every comment; tolerant of the
    odd tokenize error (a file that does not tokenize will fail the AST
    parse anyway and be reported there)."""
    out = []
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out.append((tok.start[0], tok.start[1], tok.string))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass
    return out


def parse_waivers(source: str, path: str) -> WaiverTable:
    table = WaiverTable()
    lines = source.splitlines()
    for line, col, text in _comment_tokens(source):
        m = _DIRECTIVE_RE.search(text)
        if m is None:
            if re.search(r"#\s*lsk:", text):
                table.errors.append(Finding(
                    "waiver", path, line,
                    f"unparseable lsk directive {text.strip()!r} — expected "
                    "`# lsk: allow[rule] reason` or `# lsk: holds[lock]`"))
            continue
        kind = m.group("kind")
        args = [a.strip() for a in m.group("args").split(",") if a.strip()]
        reason = m.group("reason").strip()
        # a comment alone on its line covers the NEXT line; a trailing
        # comment covers its own line
        standalone = (line - 1 < len(lines)
                      and lines[line - 1][:col].strip() == "")
        target = line + 1 if standalone else line
        if kind == "holds":
            if not args:
                table.errors.append(Finding(
                    "waiver", path, line,
                    "holds[] names no lock attribute"))
                continue
            table.holds.setdefault(target, []).extend(args)
            continue
        if not args:
            table.errors.append(Finding(
                "waiver", path, line, "allow[] names no rule"))
            continue
        if not reason:
            table.errors.append(Finding(
                "waiver", path, line,
                f"allow[{','.join(args)}] has no reason — every waiver "
                "must say why it is sound"))
            continue
        bad = [a for a in args if a not in RULES]
        if bad:
            table.errors.append(Finding(
                "waiver", path, line,
                f"allow[] names unknown rule(s) {bad} (known: "
                f"{sorted(RULES)})"))
            continue
        dst = table.allows.setdefault(target, {})
        for rule in args:
            dst[rule] = reason
    return table
