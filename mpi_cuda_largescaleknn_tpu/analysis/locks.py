"""Lock discipline + acquisition-order analysis.

Two checks over the package AST:

1. **guarded_by discipline** (rule ``lock-guard``): a class declares, via
   the PEP 526 annotation convention of analysis/annotations.py::

       self.batches: guarded_by("_cond") = 0

   and the checker proves every OTHER read/write of ``self.batches``
   inside the class happens under ``with self.<lock>``. ``__init__`` is
   exempt (the instance is not shared yet). Methods carrying
   ``# lsk: holds[_cond]`` are checked as if the lock were held, and
   their same-class call sites must hold it (rule ``lock-holds``).
   The proof is per-class and ``self``-rooted: cross-object accesses
   (``ep.health.state`` from another module) are outside its reach — the
   convention is that every such surface goes through a locked snapshot
   method of the owning class (see docs/ANALYSIS.md).

2. **lock-order graph** (rule ``lock-order``): every ``with self.X``
   acquisition is a node ``Class.X``. An edge A -> B means some code
   path acquires B while holding A — directly (nested ``with``) or one
   call deep (a method invoked under A whose resolved body acquires B;
   resolution is by method NAME across all analyzed classes, the
   deliberately-conservative choice: a false edge can only ADD cycles,
   never hide one). A cycle in the graph is a potential deadlock between
   the batcher workers, ``HealthMonitor.check_once``, and HTTP handler
   threads — exactly the threads that share these locks.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from mpi_cuda_largescaleknn_tpu.analysis.findings import Finding
from mpi_cuda_largescaleknn_tpu.analysis.waivers import WaiverTable

_LOCK_FACTORIES = ("Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore")
#: nested re-acquisition of a plain Lock is a GUARANTEED self-deadlock;
#: RLock/Condition nest legally (Condition's default inner lock is an
#: RLock), and a counting Semaphore(n>=2) may be acquired twice — the
#: count is invisible statically, so semaphores are not flagged either
_SELF_DEADLOCK_FACTORIES = ("Lock",)


@dataclass
class ClassInfo:
    name: str
    path: str
    node: ast.ClassDef
    #: base-class simple names (for guarded/lock inheritance resolution)
    bases: list[str] = field(default_factory=list)
    #: attr name -> declared lock attr name (from guarded_by annotations)
    guarded: dict[str, str] = field(default_factory=dict)
    #: attr names assigned a threading.Lock/Condition/... in any method
    lock_attrs: set[str] = field(default_factory=set)
    #: lock attr name -> factory leaf name ("Lock", "RLock", ...)
    lock_kinds: dict[str, str] = field(default_factory=dict)
    #: method name -> FunctionDef
    methods: dict[str, ast.FunctionDef] = field(default_factory=dict)
    #: method name -> locks (attr names) the method acquires directly
    acquires: dict[str, set[str]] = field(default_factory=dict)
    #: method name -> [(held lock attr names, called method name)]
    calls_under: dict[str, list[tuple[frozenset, str]]] = (
        field(default_factory=dict))
    #: method name -> [(held lock attr names, acquired lock attr name)]
    acq_events: dict[str, list[tuple[frozenset, str]]] = (
        field(default_factory=dict))


def _guard_decl(node: ast.AnnAssign) -> tuple[str, str] | None:
    """(attr, lock) for ``self.attr: guarded_by("lock") = ...``."""
    t = node.target
    if not (isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name)
            and t.value.id == "self"):
        return None
    ann = node.annotation
    if (isinstance(ann, ast.Call)
            and isinstance(ann.func, (ast.Name, ast.Attribute))
            and (_name := (ann.func.id if isinstance(ann.func, ast.Name)
                           else ann.func.attr)) == "guarded_by"
            and ann.args and isinstance(ann.args[0], ast.Constant)
            and isinstance(ann.args[0].value, str)):
        del _name
        return t.attr, ann.args[0].value
    return None


def _self_attr(node: ast.AST) -> str | None:
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _lock_factory_assign(node: ast.Assign) -> tuple[str, str] | None:
    """(attr, factory) for ``self.X = threading.Lock()`` assignments."""
    if not (isinstance(node.value, ast.Call)):
        return None
    fn = node.value.func
    leaf = (fn.attr if isinstance(fn, ast.Attribute)
            else fn.id if isinstance(fn, ast.Name) else "")
    if leaf not in _LOCK_FACTORIES:
        return None
    for t in node.targets:
        attr = _self_attr(t)
        if attr:
            return attr, leaf
    return None


def collect_class(node: ast.ClassDef, path: str) -> ClassInfo:
    info = ClassInfo(node.name, path, node)
    for b in node.bases:
        if isinstance(b, ast.Name):
            info.bases.append(b.id)
        elif isinstance(b, ast.Attribute):
            info.bases.append(b.attr)
    for item in node.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            info.methods[item.name] = item
            for sub in ast.walk(item):
                if isinstance(sub, ast.AnnAssign):
                    decl = _guard_decl(sub)
                    if decl:
                        info.guarded[decl[0]] = decl[1]
                elif isinstance(sub, ast.Assign):
                    assign = _lock_factory_assign(sub)
                    if assign:
                        info.lock_attrs.add(assign[0])
                        info.lock_kinds[assign[0]] = assign[1]
    return info


def collect_classes(tree: ast.AST, path: str) -> list[ClassInfo]:
    return [collect_class(node, path) for node in ast.walk(tree)
            if isinstance(node, ast.ClassDef)]


def resolve_inheritance(classes: list[ClassInfo]) -> None:
    """Propagate guarded/lock declarations down name-resolved bases so a
    subclass (e.g. RoutedPodFanout(PodFanout)) is checked against the
    locks its parent constructed. Name-based and iterated to fixpoint;
    external bases (http.server classes etc.) contribute nothing."""
    by_name = {c.name: c for c in classes}
    changed = True
    while changed:
        changed = False
        for cls in classes:
            for base_name in cls.bases:
                base = by_name.get(base_name)
                if base is None:
                    continue
                for attr, lock in base.guarded.items():
                    if attr not in cls.guarded:
                        cls.guarded[attr] = lock
                        changed = True
                new_locks = base.lock_attrs - cls.lock_attrs
                if new_locks:
                    cls.lock_attrs |= new_locks
                    changed = True
                for attr, kind in base.lock_kinds.items():
                    if attr not in cls.lock_kinds:
                        cls.lock_kinds[attr] = kind
                        changed = True


class _MethodChecker(ast.NodeVisitor):
    """Walk one method body tracking the set of self-locks held."""

    def __init__(self, cls: ClassInfo, method: ast.FunctionDef,
                 waivers: WaiverTable, findings: list[Finding],
                 initial_held: frozenset):
        self.cls = cls
        self.method = method
        self.waivers = waivers
        self.findings = findings
        self.held: set[str] = set(initial_held)
        # only REAL acquisitions (with-blocks) count for the order graph;
        # holds[...] contracts mean the caller already owns the lock
        self.acquired: set[str] = set()
        self.calls: list[tuple[frozenset, str]] = []
        self.acq_events: list[tuple[frozenset, str]] = []

    # nested defs get their own checker pass with the same initial held
    # set as the point of DEFINITION would be wrong (closures run later);
    # be conservative: check them as if no lock were held unless the
    # enclosing lock is held for the whole lifetime — undecidable, so we
    # treat nested function bodies as lock-free contexts.
    def visit_FunctionDef(self, node):
        if node is self.method:
            self.generic_visit(node)
            return
        sub = _MethodChecker(self.cls, node, self.waivers, self.findings,
                             frozenset())
        sub.visit_body(node)
        self.acquired |= sub.acquired
        self.calls += sub.calls
        self.acq_events += sub.acq_events

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda):
        # a lambda escapes the region it is defined in (executor
        # callbacks, sort keys) and may run on any thread later — its
        # body gets the same conservative lock-free treatment as nested
        # defs; default values DO evaluate here, in the current context
        for d in list(node.args.defaults) + [
                kd for kd in node.args.kw_defaults if kd is not None]:
            self.visit(d)
        sub = _MethodChecker(self.cls, self.method, self.waivers,
                             self.findings, frozenset())
        sub.visit(node.body)
        self.acquired |= sub.acquired
        self.calls += sub.calls
        self.acq_events += sub.acq_events

    def visit_body(self, node):
        for stmt in node.body:
            self.visit(stmt)

    def visit_With(self, node: ast.With):
        new = []
        for item in node.items:
            attr = _self_attr(item.context_expr)
            if attr and (attr in self.cls.lock_attrs
                         or attr in self.cls.guarded.values()):
                if attr in self.held or attr in new:
                    # re-acquisition of an already-held lock: a plain
                    # Lock self-deadlocks right here (the order graph
                    # can't see it — its edge-adders drop src == dst);
                    # reentrant/counting kinds nest legally, and the
                    # attr must NOT go into `new` either way or the
                    # inner exit would release the OUTER hold and every
                    # later guarded access would false-positive
                    kind = self.cls.lock_kinds.get(attr)
                    if kind in _SELF_DEADLOCK_FACTORIES:
                        self._finding(
                            "lock-order", item.context_expr,
                            f"{self.cls.name}.{attr} (threading.{kind}) "
                            f"re-acquired in {self.method.name}() while "
                            "already held — non-reentrant: guaranteed "
                            "self-deadlock")
                else:
                    self.acq_events.append(
                        (frozenset(self.held | set(new)), attr))
                    new.append(attr)
            # visit the context expression itself (it may read attrs)
            self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        self.held.update(new)
        self.acquired.update(new)
        for stmt in node.body:
            self.visit(stmt)
        for attr in new:
            self.held.discard(attr)

    visit_AsyncWith = visit_With

    def visit_Attribute(self, node: ast.Attribute):
        attr = _self_attr(node)
        if attr:
            lock = self.cls.guarded.get(attr)
            if lock is not None and lock not in self.held:
                self._finding(
                    "lock-guard", node,
                    f"{self.cls.name}.{attr} is guarded_by('{lock}') but "
                    f"accessed in {self.method.name}() without holding "
                    f"self.{lock}")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        attr = _self_attr(node.func)
        if attr is not None:
            # same-class call: record for the order graph and enforce any
            # holds[...] contract
            self.calls.append((frozenset(self.held), attr))
            target = self.cls.methods.get(attr)
            if target is not None:
                for lock in self.waivers.holds_for(target.lineno):
                    if lock not in self.held:
                        self._finding(
                            "lock-holds", node,
                            f"{self.cls.name}.{attr}() requires "
                            f"self.{lock} held (lsk: holds) but "
                            f"{self.method.name}() calls it without")
        elif isinstance(node.func, ast.Attribute):
            # cross-object call — resolved by NAME for the order graph
            self.calls.append((frozenset(self.held), node.func.attr))
        self.generic_visit(node)

    def _finding(self, rule: str, node: ast.AST, msg: str) -> None:
        reason = self.waivers.waiver_for(rule, node.lineno)
        self.findings.append(Finding(rule, self.cls.path, node.lineno, msg,
                                     waived=reason is not None,
                                     waiver_reason=reason))


def check_lock_discipline(classes: list[ClassInfo],
                          waivers_by_path: dict[str, WaiverTable]
                          ) -> list[Finding]:
    """Discipline findings over already-collected (and inheritance-
    resolved) classes; fills each class's acquisition facts for the
    order graph as a side effect."""
    findings: list[Finding] = []
    for cls in classes:
        waivers = waivers_by_path[cls.path]
        for name, fn in cls.methods.items():
            if name == "__init__":
                # still record acquisitions for the order graph, but the
                # instance is unshared: no discipline findings
                silent: list[Finding] = []
                checker = _MethodChecker(cls, fn, waivers, silent,
                                         frozenset())
            else:
                held0 = frozenset(
                    lock for lock in waivers.holds_for(fn.lineno))
                checker = _MethodChecker(cls, fn, waivers, findings, held0)
            checker.visit_body(fn)
            cls.acquires[name] = set(checker.acquired)
            cls.calls_under[name] = checker.calls
            cls.acq_events[name] = checker.acq_events
    return findings


# ------------------------------------------------------------- order graph


def build_lock_order(classes: list[ClassInfo]
                     ) -> tuple[set[tuple[str, str]], list[list[str]]]:
    """(edges, cycles) over lock nodes ``Class.attr``.

    A method's transitive acquisition set is computed by fixpoint over
    the name-resolved call graph (bounded by the finite lock set), then
    every (held, call) fact contributes edges held -> acquired(call).
    """
    # method name -> [(class, method)] across every analyzed class
    by_name: dict[str, list[tuple[ClassInfo, str]]] = {}
    for cls in classes:
        for m in cls.methods:
            by_name.setdefault(m, []).append((cls, m))

    # transitive: locks (as Class.attr) a call to `name` may acquire
    def qualify(cls: ClassInfo, locks) -> set[str]:
        return {f"{cls.name}.{lk}" for lk in locks}

    trans: dict[tuple[str, str], set[str]] = {
        (cls.name, m): qualify(cls, cls.acquires.get(m, ()))
        for cls in classes for m in cls.methods}
    changed = True
    while changed:
        changed = False
        for cls in classes:
            for m in cls.methods:
                cur = trans[(cls.name, m)]
                for _held, callee in cls.calls_under.get(m, ()):
                    for tcls, tm in by_name.get(callee, ()):
                        extra = trans[(tcls.name, tm)] - cur
                        if extra:
                            cur |= extra
                            changed = True

    edges: set[tuple[str, str]] = set()
    for cls in classes:
        for m in cls.methods:
            # direct nesting: `with A: ... with B:` inside one body
            for held, lock in cls.acq_events.get(m, ()):
                dst = f"{cls.name}.{lock}"
                for src in qualify(cls, held):
                    if src != dst:
                        edges.add((src, dst))
            # one call deep: a method invoked while holding A whose
            # name-resolved body (transitively) acquires B
            for held, callee in cls.calls_under.get(m, ()):
                if not held:
                    continue
                held_q = qualify(cls, held)
                for tcls, tm in by_name.get(callee, ()):
                    for dst in trans[(tcls.name, tm)]:
                        for src in held_q:
                            if src != dst:
                                edges.add((src, dst))

    cycles = _find_cycles(edges)
    return edges, cycles


def _find_cycles(edges: set[tuple[str, str]]) -> list[list[str]]:
    """Strongly-connected components of size > 1 (plus self-loops),
    reported as sorted node lists — deterministic output for CI diffs."""
    graph: dict[str, set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    out: list[list[str]] = []
    counter = [0]

    def strongconnect(v: str):
        # iterative Tarjan: (node, iterator) frames
        work = [(v, iter(sorted(graph[v])))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph[w]))))
                    advanced = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1 or (node, node) in edges:
                    out.append(sorted(comp))

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    return sorted(out)


def lock_order_findings(classes: list[ClassInfo]
                        ) -> tuple[list[Finding], list[str]]:
    edges, cycles = build_lock_order(classes)
    path_of = {c.name: c.path for c in classes}
    findings = [
        Finding("lock-order",
                path_of.get(cycle[0].split(".")[0], "<order-graph>"),
                cycle_line(classes, cycle),
                "lock acquisition-order cycle (potential deadlock): "
                + " <-> ".join(cycle))
        for cycle in cycles]
    edge_strs = [f"{a} -> {b}" for a, b in sorted(edges)]
    return findings, edge_strs


def cycle_line(classes: list[ClassInfo], cycle: list[str]) -> int:
    """Anchor a cycle finding at the declaring class's def line."""
    name = cycle[0].split(".")[0]
    for c in classes:
        if c.name == name:
            return c.node.lineno
    return 1
