"""Source-level annotations the static-analysis passes key on.

``guarded_by`` is used as a PEP 526 attribute annotation::

    from mpi_cuda_largescaleknn_tpu.analysis import guarded_by

    class Batcher:
        def __init__(self):
            self._cond = threading.Condition()
            self.batches: guarded_by("_cond") = 0

Every module in ``serve/`` has ``from __future__ import annotations``, so
the annotation is never evaluated at runtime — it costs nothing and adds
no import-order hazards; it exists purely for ``analysis/locks.py``,
which proves (per class) that every read/write of an annotated attribute
happens inside the declared ``with self.<lock>`` block. The function is
still a real callable so the convention also works in modules WITHOUT
deferred annotations (it returns ``object``, a valid if vacuous type).

Method-level contracts ride comments instead (a ``def`` cannot carry a
PEP 526 annotation): ``# lsk: holds[_lock]`` on the ``def`` line declares
"callers must hold ``self._lock``" — the checker then verifies the body
AS IF the lock were held and flags any same-class call site that invokes
the method without it (analysis/waivers.py parses the grammar).
"""

from __future__ import annotations


def guarded_by(lock_attr: str, *_extra) -> type:
    """Annotation marker: the attribute may only be read or written while
    holding ``self.<lock_attr>`` (a ``threading.Lock`` / ``RLock`` /
    ``Condition`` attribute of the same instance). Checked statically by
    ``analysis/locks.py``; a no-op at runtime."""
    if not isinstance(lock_attr, str) or not lock_attr:
        raise TypeError("guarded_by() takes the lock attribute NAME, "
                        f"e.g. guarded_by('_lock'); got {lock_attr!r}")
    return object
