"""lskcheck: project-native static analysis for the serving stack.

The stack's two load-bearing guarantees — bitwise parity across merge
modes/hosts (the exact-kNN contract) and a threaded serving layer that
survives host loss — were enforced only by runtime tests until this
package. Nothing stopped a new ``time.time()`` in a fold path, an
unguarded read of ``HostHealth`` state, or a silently-widened AOT bucket
signature from landing. ``lskcheck`` turns those invariants into a
machine-checked, CI-blocking form:

- ``locks``       — the ``guarded_by("_lock")`` annotation convention for
                    shared attributes, an AST checker proving every
                    read/write of a guarded attribute happens inside the
                    declared ``with self._lock`` block, and a lock-
                    acquisition-order graph that flags potential
                    inversions between threads.
- ``determinism`` — bans wall-clock and unseeded RNG in deterministic
                    paths, float ``==`` on distances, unstable sorts in
                    tie-sensitive code, dict-iteration-order-dependent
                    folds, and silent exception swallowing.
- ``aot``         — ``jax.eval_shape``-traces every engine shape-bucket
                    program on the CPU fixture and diffs the signature
                    table against the committed ``docs/aot_contract.json``
                    golden, catching recompile-risk and dtype drift
                    without a TPU.

Every suppression must be auditable: ``# lsk: allow[rule] reason``
(analysis/waivers.py). Entry point: ``tools/lskcheck.py``; rule catalog:
``docs/ANALYSIS.md``.

This module stays import-light (no jax, no numpy) so serving code can
import ``guarded_by`` for free.
"""

from mpi_cuda_largescaleknn_tpu.analysis.annotations import guarded_by

__all__ = ["guarded_by", "run_repo"]


def run_repo(*args, **kwargs):
    """Lazy alias for :func:`analysis.runner.run_repo` (keeps the package
    root import-light for the serving modules that only need
    ``guarded_by``)."""
    from mpi_cuda_largescaleknn_tpu.analysis.runner import run_repo as _run

    return _run(*args, **kwargs)
