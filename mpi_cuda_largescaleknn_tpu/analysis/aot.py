"""AOT shape-bucket contract: trace every engine program, diff a golden.

The serving engine's whole shape discipline (TPU-KNN, arXiv:2206.14286)
is that a served shape can never silently retrace: programs are
AOT-compiled per (engine, merge, qpad, query_buckets, score_dtype) key
and ``compile_count`` is an honest counter. What that discipline does
NOT catch by itself is drift in the programs' SIGNATURES — a widened
operand dtype, an extra resident input, a reshaped output — which is
recompile-risk and wire-format risk that only shows up under load or on
a real TPU.

This pass pins the full signature table: it builds small deterministic
CPU fixture engines (2 mesh shards — no TPU needed), runs
``jax.eval_shape`` over every shape-bucket program exactly as
``_get_executable`` would build it, and diffs the resulting
input/output aval table against the committed golden
``docs/aot_contract.json``. Any difference — program added, program
gone, signature changed, bucket geometry moved — is an ``aot-contract``
finding. Intentional changes regenerate the golden
(``python tools/lskcheck.py --write-aot-golden``) and the diff shows up
in review as a JSON change, which is the point.

Shapes depend only on the fixture constants below (never on point
values, devices beyond the pinned mesh, or wall-clock), so the table is
bit-stable across machines.
"""

from __future__ import annotations

import json
import os

import numpy as np

from mpi_cuda_largescaleknn_tpu.analysis.findings import Finding

CONTRACT_RELPATH = os.path.join("docs", "aot_contract.json")
CONTRACT_VERSION = 1

#: fixture constants — part of the contract: changing any of them
#: legitimately regenerates the golden
FIXTURE = {"n_points": 192, "k": 4, "num_shards": 2,
           "max_batch": 16, "min_batch": 8}

#: engine configurations whose program families the contract pins: the
#: serving matrix's load-bearing corners — host vs device merge, exact
#: f32 vs MXU bf16 (high-D so the matmul path is actually taken), the
#: routed candidates emission, and the flat engine
CONFIGS = (
    {"engine": "tiled", "merge": "host", "score_dtype": "f32", "dim": 3,
     "emit": "final"},
    {"engine": "tiled", "merge": "device", "score_dtype": "f32", "dim": 3,
     "emit": "final"},
    {"engine": "tiled", "merge": "device", "score_dtype": "bf16", "dim": 32,
     "emit": "final"},
    {"engine": "tiled", "merge": "device", "score_dtype": "f32", "dim": 3,
     "emit": "candidates"},
    {"engine": "bruteforce", "merge": "device", "score_dtype": "f32",
     "dim": 3, "emit": "final"},
)


def fixture_points(n: int, dim: int) -> np.ndarray:
    """Deterministic low-discrepancy points in [0, 1)^dim — a Weyl
    sequence, so no RNG is involved at all (this module must satisfy its
    own determinism rules)."""
    i = np.arange(1, n * dim + 1, dtype=np.float64)
    return ((i * 0.6180339887498949) % 1.0).reshape(
        n, dim).astype(np.float32)


def _aval_str(aval) -> str:
    return f"{aval.dtype.name}[{','.join(str(d) for d in aval.shape)}]"


def config_key(cfg: dict) -> str:
    return (f"{cfg['engine']}|{cfg['merge']}|{cfg['score_dtype']}"
            f"|d{cfg['dim']}|emit={cfg['emit']}")


def trace_contract() -> dict:
    """Build every fixture engine and eval_shape its program family."""
    import jax

    from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
    from mpi_cuda_largescaleknn_tpu.serve.engine import ResidentKnnEngine

    mesh = get_mesh(FIXTURE["num_shards"])
    out_configs = []
    for cfg in CONFIGS:
        pts = fixture_points(FIXTURE["n_points"], cfg["dim"])
        engine = ResidentKnnEngine(
            pts, FIXTURE["k"], mesh=mesh, engine=cfg["engine"],
            merge=cfg["merge"], score_dtype=cfg["score_dtype"],
            emit=cfg["emit"], max_batch=FIXTURE["max_batch"],
            min_batch=FIXTURE["min_batch"])
        programs = {}
        for qpad in engine.shape_buckets:
            qb = engine.query_buckets[qpad]
            fn = engine._build_query_fn(engine.engine_name, qpad, qb)
            args = engine._resident_args(engine.engine_name)
            q0 = jax.ShapeDtypeStruct((qpad, engine.dim), np.float32)
            # the per-query init-radius operand (certified radius
            # seeding, serve/qcache.py) — part of every program's arity
            r0 = jax.ShapeDtypeStruct((qpad,), np.float32)
            out = jax.eval_shape(fn, *args, q0, r0)
            programs[f"q{qpad}|B{qb}"] = {
                "in": [_aval_str(a) for a in args]
                      + [_aval_str(q0), _aval_str(r0)],
                "out": [_aval_str(o) for o in out],
            }
        out_configs.append({
            "key": config_key(cfg), **cfg,
            "shape_buckets": list(engine.shape_buckets),
            "query_buckets": {str(q): b for q, b in
                              sorted(engine.query_buckets.items())},
            "canonical_ties": engine.canonical_ties,
            "score_mode": engine.score_mode,
            "programs": programs,
        })
    return {"version": CONTRACT_VERSION, "fixture": dict(FIXTURE),
            "configs": out_configs}


def write_contract(contract: dict, path: str) -> None:
    with open(path, "w") as fh:
        json.dump(contract, fh, indent=2, sort_keys=True)
        fh.write("\n")


def diff_contract(contract: dict, golden_path: str) -> list[Finding]:
    """Findings for every difference between the traced table and the
    committed golden. The golden missing entirely is itself a finding —
    the gate must fail loudly, not silently pass, on a fresh clone."""
    rel = os.path.join("docs", "aot_contract.json")
    if not os.path.exists(golden_path):
        return [Finding("aot-contract", rel, 1,
                        "golden contract file is missing — generate it "
                        "with `python tools/lskcheck.py "
                        "--write-aot-golden` and commit it")]
    with open(golden_path) as fh:
        golden = json.load(fh)
    findings: list[Finding] = []

    def emit(msg: str) -> None:
        findings.append(Finding("aot-contract", rel, 1, msg))

    if golden.get("version") != contract["version"]:
        emit(f"contract version drifted: golden "
             f"{golden.get('version')} vs traced {contract['version']}")
    if golden.get("fixture") != contract["fixture"]:
        emit(f"fixture constants drifted: golden {golden.get('fixture')} "
             f"vs traced {contract['fixture']}")
    gold_by_key = {c["key"]: c for c in golden.get("configs", ())}
    new_by_key = {c["key"]: c for c in contract["configs"]}
    for key in sorted(gold_by_key.keys() - new_by_key.keys()):
        emit(f"engine config {key} is in the golden but no longer "
             "traced — a serving configuration silently disappeared")
    for key in sorted(new_by_key.keys() - gold_by_key.keys()):
        emit(f"engine config {key} is traced but not in the golden — "
             "regenerate the golden to adopt it")
    for key in sorted(new_by_key.keys() & gold_by_key.keys()):
        g, n = gold_by_key[key], new_by_key[key]
        for fld in ("shape_buckets", "query_buckets", "canonical_ties",
                    "score_mode"):
            if g.get(fld) != n.get(fld):
                emit(f"{key}: {fld} drifted: golden {g.get(fld)} vs "
                     f"traced {n.get(fld)} — AOT bucket geometry changed")
        gp, np_ = g.get("programs", {}), n.get("programs", {})
        for pk in sorted(gp.keys() - np_.keys()):
            emit(f"{key}: program {pk} gone — a shape bucket vanished "
                 "(recompile risk for served shapes)")
        for pk in sorted(np_.keys() - gp.keys()):
            emit(f"{key}: program {pk} is new — regenerate the golden "
                 "to adopt the bucket")
        for pk in sorted(np_.keys() & gp.keys()):
            for side in ("in", "out"):
                if gp[pk].get(side) != np_[pk].get(side):
                    emit(f"{key}: program {pk} {side!r} signature "
                         f"drifted: golden {gp[pk].get(side)} vs traced "
                         f"{np_[pk].get(side)} — dtype/shape drift in "
                         "the AOT program contract")
    return findings
