"""Small integer helpers (host-side, static-shape arithmetic) plus the
3-D Morton (Z-order) encoder the serving layer sorts query batches with.

The Morton code interleaves the bits of the three quantized coordinates, so
points close on the curve are close in space (the converse holds up to the
curve's O(1) boundary jumps). Sorting a query batch by code makes contiguous
slices spatially tight — exactly what the tiled traversal's per-query-bucket
prune radius wants (serve/engine.py). Everything here is numpy on the host:
the sort happens at admission time, before the batch is staged on device.

Relation to ``io/partition_file.py morton_codes`` (the file pre-partitioner):
that variant reproduces the reference C++ ``morton3`` bit for bit (x in the
HIGH interleave position, float32 quantization arithmetic) and must not
drift from it; this one is the serving-side encoder (x LOW, float64
quantization, out-of-box clamping, sentinel rows -> pads-last max code).
They share the ``_part1by2`` bit-dilation core below — fix dilation bugs
here, once.
"""

import numpy as np


def cdiv(a: int, b: int) -> int:
    """Ceiling division (the reference's ``cukd::divRoundUp``,
    used for launch geometry at unorderedDataVariant.cu:199)."""
    return -(-a // b)


def round_up(a: int, b: int) -> int:
    """Round ``a`` up to the next multiple of ``b``."""
    return cdiv(a, b) * b


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (n >= 1)."""
    p = 1
    while p < n:
        p <<= 1
    return p


#: grid resolution per axis: 21 bits x 3 axes = 63 bits — one uint64 code
MORTON_BITS = 21

#: code every padding/sentinel row maps to: ABOVE any real interleaved code
#: (real codes use at most 63 bits), so a stable sort puts pads last
MORTON_PAD_CODE = np.uint64(0xFFFFFFFFFFFFFFFF)


def _part1by2(v: np.ndarray) -> np.ndarray:
    """Spread the low 21 bits of each uint64 so bit i lands at bit 3*i
    (the classic 64-bit magic-mask dilation)."""
    v = v.astype(np.uint64)
    v &= np.uint64(0x1FFFFF)
    v = (v | (v << np.uint64(32))) & np.uint64(0x1F00000000FFFF)
    v = (v | (v << np.uint64(16))) & np.uint64(0x1F0000FF0000FF)
    v = (v | (v << np.uint64(8))) & np.uint64(0xF00F00F00F00F00F)
    v = (v | (v << np.uint64(4))) & np.uint64(0x30C30C30C30C30C3)
    v = (v | (v << np.uint64(2))) & np.uint64(0x9249249249249249)
    return v


def _compact1by2(v: np.ndarray) -> np.ndarray:
    """Inverse of ``_part1by2``: gather every third bit back together."""
    v = v.astype(np.uint64)
    v &= np.uint64(0x9249249249249249)
    v = (v | (v >> np.uint64(2))) & np.uint64(0x30C30C30C30C30C3)
    v = (v | (v >> np.uint64(4))) & np.uint64(0xF00F00F00F00F00F)
    v = (v | (v >> np.uint64(8))) & np.uint64(0x1F0000FF0000FF)
    v = (v | (v >> np.uint64(16))) & np.uint64(0x1F00000000FFFF)
    v = (v | (v >> np.uint64(32))) & np.uint64(0x1FFFFF)
    return v


def morton_interleave(grid: np.ndarray) -> np.ndarray:
    """u64[n] Morton codes from integer grid coords ``[n, 3]`` in
    [0, 2**MORTON_BITS). Bit-exact round trip with ``morton_deinterleave``;
    monotone per axis (fixing two axes, a larger third axis coordinate never
    yields a smaller code)."""
    g = np.asarray(grid, np.uint64)
    return (_part1by2(g[:, 0])
            | (_part1by2(g[:, 1]) << np.uint64(1))
            | (_part1by2(g[:, 2]) << np.uint64(2)))


def morton_deinterleave(codes: np.ndarray) -> np.ndarray:
    """Integer grid coords ``[n, 3]`` back out of u64 Morton codes."""
    c = np.asarray(codes, np.uint64)
    return np.stack([_compact1by2(c),
                     _compact1by2(c >> np.uint64(1)),
                     _compact1by2(c >> np.uint64(2))], axis=1)


def morton_codes(points: np.ndarray, lo, hi,
                 bits: int = MORTON_BITS) -> np.ndarray:
    """u64[n] Morton codes of f32 points quantized to a ``2**bits`` grid
    over the [lo, hi] box (out-of-box coordinates clamp to the faces, so
    queries outside the index bbox still order sensibly along its surface).
    Sentinel/padding rows (core.types.PAD_SENTINEL coords) map to
    ``MORTON_PAD_CODE`` — strictly above every real code, so a stable sort
    leaves them last."""
    from mpi_cuda_largescaleknn_tpu.core.types import PAD_SENTINEL

    pts = np.asarray(points, np.float32).reshape(-1, 3)
    lo = np.asarray(lo, np.float32).reshape(3)
    hi = np.asarray(hi, np.float32).reshape(3)
    top = np.float64((1 << bits) - 1)
    ext = (hi - lo).astype(np.float64)
    scale = np.where(ext > 0, top / np.where(ext > 0, ext, 1.0), 0.0)
    grid = np.clip((pts.astype(np.float64) - lo) * scale, 0.0, top)
    codes = morton_interleave(grid.astype(np.uint64))
    valid = pts[:, 0] < PAD_SENTINEL / 2
    return np.where(valid, codes, MORTON_PAD_CODE)


def morton_argsort(points: np.ndarray, lo, hi) -> np.ndarray:
    """Stable permutation sorting ``points`` by Morton code (pads last,
    equal codes keep input order) — the serving admission sort."""
    return np.argsort(morton_codes(points, lo, hi), kind="stable")


def aabb_lower_bound_dist2(queries: np.ndarray, lo: np.ndarray,
                           hi: np.ndarray) -> np.ndarray:
    """f64[n, S] squared lower-bound distance from each query to each
    axis-aligned box: per axis the distance to the nearest face (0 inside
    the slab), summed over axes — the classic kd-bounds prune, here the
    pod routing decision (serve/frontend.py ``PodBoundsTable``). A point
    INSIDE box s can never be closer to q than ``sqrt(out[q, s])``, so a
    box whose bound exceeds a query's current k-th distance cannot improve
    its answer. Computed in float64 so the bound itself adds no rounding
    slack (the engines' f32 rounding is covered by the caller's
    certification slack, not here)."""
    q = np.asarray(queries, np.float64)
    lo = np.asarray(lo, np.float64)
    hi = np.asarray(hi, np.float64)
    d = np.maximum(np.maximum(lo[None] - q[:, None], q[:, None] - hi[None]),
                   0.0)
    return np.einsum("nsd,nsd->ns", d, d)
