"""Small integer helpers (host-side, static-shape arithmetic)."""


def cdiv(a: int, b: int) -> int:
    """Ceiling division (the reference's ``cukd::divRoundUp``,
    used for launch geometry at unorderedDataVariant.cu:199)."""
    return -(-a // b)


def round_up(a: int, b: int) -> int:
    """Round ``a`` up to the next multiple of ``b``."""
    return cdiv(a, b) * b


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (n >= 1)."""
    p = 1
    while p < n:
        p <<= 1
    return p
