"""Persistent XLA compile-cache bootstrap, shared by bench/tune/probe.

On-chip full-model compile was measured at ~220s for the 250K config
(round5/chip/bench_fast.out) — it dominates any attempt budget. Enabling
jax's persistent compilation cache makes a completed compile survive the
process, so bench retries, tune cells at a repeated geometry, and
separate agenda steps pay it once per session. Entries are keyed by
HLO + backend, so cpu-fallback and TPU programs coexist in one dir.

Must be called BEFORE the first jax import in the process (env vars are
read at backend init). All call sites use `setdefault`, so an operator
export (e.g. round5/chip_session.sh) always wins.
"""
from __future__ import annotations

import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def enable_persistent_cache(cache_dir: str | None = None) -> str:
    """Point JAX_COMPILATION_CACHE_DIR at a repo-local dir (idempotent)."""
    cache_dir = os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        cache_dir or os.path.join(_REPO_ROOT, ".jax_cache"))
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
    try:
        os.makedirs(cache_dir, exist_ok=True)
    except OSError as e:
        # jax runs fine uncached, but silently repaying the ~220s compile on
        # every launch is an operational failure an operator must hear about
        sys.stderr.write(
            f"warning: compile cache dir {cache_dir!r} is not writable "
            f"({e}); every XLA compile will be repaid each process\n")
    return cache_dir
