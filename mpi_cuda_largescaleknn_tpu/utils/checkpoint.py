"""Ring-state checkpointing.

The reference has NO checkpoint/resume: one pass, outputs written only at the
end; a lost rank = a lost run (SURVEY.md §5, unorderedDataVariant.cu:229-237).
Its candidate-list buffer is nevertheless a natural checkpointable state — the
per-query heaps fully summarize all rounds folded so far — and this module
adds that capability: after any ring round, (round index, heaps, resident
rotating shard) pin the exact remaining work, so a preempted multi-hour run
resumes instead of restarting.

Crash-safety: everything (arrays, round index, config fingerprint) lives in
ONE ``.npz`` written to a temp path and atomically renamed — there is no
window where the round index and the arrays can disagree. The fingerprint
includes a sampled digest of the input data, so resuming against edited
inputs fails loudly instead of folding new queries into old heaps; a
completed run clears its checkpoint (see ring_knn_stepwise) so stale results
can never be replayed as fresh ones.
"""

from __future__ import annotations

import hashlib
import json
import os

import numpy as np

_STATE = "ring_state.npz"


def data_digest(*arrays, sample_bytes: int = 1 << 16) -> str:
    """Content fingerprint of input arrays — SAMPLED, not exhaustive, so it
    stays O(sample) for billion-point inputs: hashes shape+dtype, the first
    and last ``sample_bytes``, and an even stride through the middle. Catches
    any realistic "same shapes, different dataset" mixup."""
    h = hashlib.sha256()
    for a in arrays:
        a = np.ascontiguousarray(np.asarray(a))
        h.update(repr((a.shape, str(a.dtype))).encode())
        b = a.reshape(-1).view(np.uint8)
        if b.nbytes <= 3 * sample_bytes:
            h.update(b.tobytes())
        else:
            h.update(b[:sample_bytes].tobytes())
            h.update(b[-sample_bytes:].tobytes())
            stride = max(1, b.nbytes // sample_bytes)
            h.update(b[::stride].tobytes())
    return h.hexdigest()


def fingerprint(**kv) -> dict:
    """Config identity a checkpoint is valid for (all jsonable scalars)."""
    return {k: (v if isinstance(v, (int, str, bool)) else float(v))
            for k, v in kv.items()}


def save_ring_state(ckpt_dir: str, round_idx: int, arrays: dict,
                    manifest: dict) -> None:
    """Atomically persist ``arrays`` (name -> array) at ``round_idx``."""
    os.makedirs(ckpt_dir, exist_ok=True)
    # np.savez appends ".npz" to names lacking it — keep the suffix last
    tmp = os.path.join(ckpt_dir, f".tmp.{os.getpid()}.{_STATE}")
    np.savez(tmp,
             __round__=np.int64(round_idx),
             __fingerprint__=np.frombuffer(
                 json.dumps(manifest, sort_keys=True).encode(), np.uint8),
             **{k: np.asarray(v) for k, v in arrays.items()})
    os.replace(tmp, os.path.join(ckpt_dir, _STATE))


def _check_fp(z, manifest: dict, ckpt_dir: str) -> None:
    saved_fp = json.loads(z["__fingerprint__"].tobytes().decode())
    want_fp = json.loads(json.dumps(manifest, sort_keys=True))
    if saved_fp != want_fp:
        raise ValueError(
            f"checkpoint at {ckpt_dir} was written for config "
            f"{saved_fp}, not {want_fp}; remove it (or pass a different "
            f"--checkpoint-dir) to start fresh")


def peek_round(ckpt_dir: str, manifest: dict):
    """Round index of a valid checkpoint, or None — WITHOUT loading the
    arrays (np.load reads entries lazily), so drivers can decide whether a
    run resumes before paying any init work the resume would discard."""
    spath = os.path.join(ckpt_dir, _STATE)
    if not os.path.exists(spath):
        return None
    with np.load(spath) as z:
        _check_fp(z, manifest, ckpt_dir)
        return int(z["__round__"])


def load_ring_state(ckpt_dir: str, manifest: dict):
    """Returns (round_idx, arrays dict) or None if absent.

    Raises ValueError when a checkpoint exists but was written for a
    different run configuration or different input data.
    """
    spath = os.path.join(ckpt_dir, _STATE)
    if not os.path.exists(spath):
        return None
    with np.load(spath) as z:
        _check_fp(z, manifest, ckpt_dir)
        rnd = int(z["__round__"])
        return rnd, {k: z[k] for k in z.files
                     if k not in ("__round__", "__fingerprint__")}


def clear(ckpt_dir: str) -> None:
    p = os.path.join(ckpt_dir, _STATE)
    if os.path.exists(p):
        os.remove(p)


def save_pytree(ckpt_dir: str, round_idx: int, tree, manifest: dict) -> None:
    """Snapshot an arbitrary pytree of arrays (leaves keyed positionally).

    Shared by the stepwise ring and demand drivers so the snapshot format
    cannot drift between them."""
    import jax

    flat, _ = jax.tree.flatten(tree)
    jax.block_until_ready(flat)
    save_ring_state(ckpt_dir, round_idx,
                    {f"a{i}": a for i, a in enumerate(flat)}, manifest)


def load_pytree(ckpt_dir: str, manifest: dict, like, sharding):
    """Restore a pytree saved by ``save_pytree``; ``like`` supplies the
    treedef, ``sharding`` the placement. Returns (round_idx, tree) or None."""
    import jax

    got = load_ring_state(ckpt_dir, manifest)
    if got is None:
        return None
    round_idx, arrs = got
    flat, treedef = jax.tree.flatten(like)
    restored = [jax.device_put(arrs[f"a{i}"], sharding)
                for i in range(len(flat))]
    return round_idx, jax.tree.unflatten(treedef, restored)
