"""JAX version compatibility shims (install-once, idempotent).

The engines target current JAX (``jax.shard_map`` with varying-manual-axes
typing, ``jax.lax.pcast``, ``jax.typeof``); container images sometimes pin
an older jax where ``shard_map`` still lives in ``jax.experimental`` and the
vma type system does not exist. Rather than littering every call site with
version checks, ``install()`` bridges the gap at the ``jax`` module level:

- ``jax.shard_map`` -> wraps ``jax.experimental.shard_map.shard_map``,
  accepting and dropping the ``check_vma`` kwarg. The old ``check_rep``
  checker is force-disabled: it predates the vma semantics the engines are
  written against (e.g. freshly-initialized replicated heap constants
  entering sharded while_loop carries) and rejects valid programs the
  current checker accepts. The check is diagnostic only — results are
  unaffected.
- ``parallel.mesh.pvary`` no-ops when ``jax.lax.pcast`` is absent (there is
  no varying type to cast to — see its own guard).

Called from ``parallel/mesh.py`` at import, i.e. before any engine can hit
``jax.shard_map``. Deliberately NOT from the package ``__init__``: importing
jax there would break ``utils/compile_cache.py``'s must-run-before-jax
contract for the CLIs.
"""

from __future__ import annotations


def shape_dtype_struct(shape, dtype, like=None):
    """``jax.ShapeDtypeStruct`` carrying ``like``'s varying-manual-axes type
    when this jax HAS vma typing (``jax.typeof``), a plain struct otherwise.

    The Pallas wrappers' out_shapes must vary over the same mesh axes as
    the candidate state under shard_map on current jax; on the container's
    older pin neither ``jax.typeof`` nor the ``vma=`` kwarg exists and the
    plain struct is the correct (and only) spelling."""
    import jax

    if like is not None and hasattr(jax, "typeof"):
        vma = getattr(jax.typeof(like), "vma", frozenset())
        try:
            return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
        except TypeError:  # vma kwarg not accepted on this jax
            pass
    return jax.ShapeDtypeStruct(shape, dtype)


def install() -> None:
    import jax

    if hasattr(jax, "shard_map"):
        return
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kwargs):
        del check_vma, kwargs  # vma typing absent on this jax; see module doc
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False)

    jax.shard_map = shard_map
