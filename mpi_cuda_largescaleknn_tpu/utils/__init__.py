from mpi_cuda_largescaleknn_tpu.utils.math import cdiv, next_pow2, round_up  # noqa: F401
