#!/bin/bash
# Window agenda #3 — what the 04:05 outage killed, cheapest-headline
# first. Run ONLY via watch3.sh (single-client tunnel).
set -u
cd /root/repo
export JAX_COMPILATION_CACHE_DIR=/root/repo/.jax_cache
export JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS=1
export JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES=0
OUT=round5/chip
stamp() { date -u +%FT%TZ; }
log() { echo "[$(stamp)] $*" | tee -a $OUT/session.log; }
run_step() { # name timeout_s cmd...
  local name=$1 tmo=$2; shift 2
  log "START $name"
  timeout "$tmo" "$@" > "$OUT/$name.out" 2> "$OUT/$name.err"
  local rc=$?
  log "END $name rc=$rc"
  return $rc
}

# 1. THE headline upgrade: 1M/k=8 at the tuned 256/G2 auto default
#    (bench auto-adopts the committed tune winner; expect >550K q/s).
run_step bench_1m_k8_tuned 1700 env BENCH_BUDGET_S=1500 python bench.py

# 2. Targeted tune cells: the outage-killed 1M confirms + k=100 sweep
#    cells, PLUS the unswept lanes/point-group neighborhood of the
#    256/G2 winner (the crossed grid only swept lanes at G1; 4096 beat
#    2048 by 12% at 128/G1). Generous per-cell cap: a SIGKILLed TPU
#    child wedged the tunnel at 04:05 (see SKILL.md).
run_step tune_missed 5400 env TUNE_TIMEOUT_S=900 \
    python -u tools/tpu_tune.py --cells round5/missed_cells.json

# 2b. Re-bench 1M/k=8 with whatever the extended sweep crowned (bench
#     auto-adopts; compile cached if the winner is a confirmed cell).
run_step bench_1m_k8_best 1200 env BENCH_BUDGET_S=1000 python bench.py

# 3. k=100 at 1M on chip (VERDICT item 4's real target).
run_step bench_1m_k100_tuned 2200 env BENCH_K=100 BENCH_BUDGET_S=2000 \
    python bench.py

# 4. 250K fast number at the tuned geometry.
run_step bench_250k_tuned 800 env BENCH_N=250000 BENCH_BUDGET_S=600 \
    python bench.py

log "agenda3 complete"
