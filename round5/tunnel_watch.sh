#!/bin/bash
# Retry TPU contact; on success, immediately run the full chip agenda
# (round5/chip_session.sh) so no tunnel-up minute is wasted.
# Single instance only (the axon tunnel is single-client).
LOCK=/root/repo/round5/.watch.lock
exec 9>"$LOCK"
flock -n 9 || { echo "another watcher holds $LOCK" >&2; exit 1; }
LOG=/root/repo/round5/tunnel_watch.log
echo "watch start $(date -u +%FT%TZ)" >> $LOG
while true; do
  # rc=0 ONLY for a real accelerator: a fast CPU fallback (plugin error
  # instead of tunnel hang) must keep the watcher alive, not fire the
  # one-shot agenda on the host backend
  timeout 240 python -c "
import sys, time, jax
t0=time.time()
ds = jax.devices()
print('CONTACT', round(time.time()-t0,1), [str(d) for d in ds],
      ds[0].device_kind)
sys.exit(0 if ds and ds[0].platform != 'cpu' else 2)
" >> $LOG 2>&1
  rc=$?
  echo "attempt rc=$rc $(date -u +%FT%TZ)" >> $LOG
  if [ $rc -eq 0 ]; then
    touch /root/repo/round5/TUNNEL_UP
    echo "TUNNEL UP -> launching chip agenda $(date -u +%FT%TZ)" >> $LOG
    bash /root/repo/round5/chip_session.sh all >> $LOG 2>&1
    echo "chip agenda exited $(date -u +%FT%TZ)" >> $LOG
    exit 0
  fi
  sleep 15
done
