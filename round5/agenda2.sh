#!/bin/bash
# Session-2 chained agenda: 1M bench first (THE deliverable), then probe
# (gates tune), tune sweep, k=100, then a 250K fast number. Each step via
# chip_session.sh so all logging/caps/cache exports stay in one place.
cd /root/repo
bash round5/chip_session.sh bench
bash round5/chip_session.sh probe && bash round5/chip_session.sh tune
bash round5/chip_session.sh k100
bash round5/chip_session.sh fast
echo "agenda2 complete $(date -u +%FT%TZ)" >> round5/chip/session.log
