#!/bin/bash
# Round-5 on-chip agenda, in strict priority order (VERDICT.md "Next round").
# The axon tunnel is single-client: this script is the ONLY TPU-touching
# process while it runs. Every step logs to round5/chip/ and is individually
# timeout-capped so one hang cannot eat the window.
#
#   bash round5/chip_session.sh            # full agenda
#   bash round5/chip_session.sh probe      # just the probe
set -u
cd /root/repo
# Persistent XLA compile cache for every step (bench children, probe,
# tune cells): on-chip full-model compile measured at ~220s for 250K —
# pay it once per shape/geometry for the whole session.
export JAX_COMPILATION_CACHE_DIR=/root/repo/.jax_cache
export JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS=1
export JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES=0
mkdir -p "$JAX_COMPILATION_CACHE_DIR"
OUT=round5/chip
mkdir -p $OUT
stamp() { date -u +%FT%TZ; }
log() { echo "[$(stamp)] $*" | tee -a $OUT/session.log; }

run_step() { # name timeout_s cmd...
  local name=$1 tmo=$2; shift 2
  log "START $name"
  timeout "$tmo" "$@" > "$OUT/$name.out" 2> "$OUT/$name.err"
  local rc=$?
  log "END $name rc=$rc"
  return $rc
}

only=${1:-all}

# Tunnel windows can be MINUTES long (01:02-01:05 UTC this session), so
# the order banks evidence cheapest-first: a fast 250K bench (~60-90s
# after contact, with bench.py's own in-attempt engine fallback serving
# as the Mosaic gate), then the 1M deliverable, then the full probe
# (warm_group matrix), then the tune sweep.

# 1. Fast bench at 250K: banks SOME real-chip number for the new kernels
#    within even a short window.
if [ "$only" = all ] || [ "$only" = fast ]; then
  run_step bench_fast 600 env BENCH_N=250000 BENCH_BUDGET_S=420 \
      python bench.py
fi
[ "$only" = fast ] && exit 0

# 2. THE deliverable: BENCH at 1M/k=8 on the chip (VERDICT item 1).
#    bench.py self-checks and falls back with stage attribution.
if [ "$only" = all ] || [ "$only" = bench ]; then
  run_step bench_1m_k8 2400 env BENCH_BUDGET_S=1800 python bench.py
  cp $OUT/bench_1m_k8.out $OUT/BENCH_candidate.json 2>/dev/null
fi
[ "$only" = bench ] && exit 0

# 3. Probe: health + Mosaic-compile of every round-5 kernel addition
#    (position fold, per-visit mask, skip-self, self_group, [1,1,2] stats,
#    segmented fold at the bucket-64 geometry that crashed the AOT
#    backend pre-refactor). tpu_probe.py always exits 0 (stage errors go
#    into its report); its on_tpu verdict gates the tune sweep below.
if [ "$only" = all ] || [ "$only" = probe ]; then
  run_step probe 1800 python -u tools/tpu_probe.py || exit 1
  grep -q '"on_tpu": true' $OUT/probe.out || {
    log "probe reports on_tpu=false — aborting agenda (CPU backend)";
    exit 1; }
fi
[ "$only" = probe ] && exit 0

# 4. Tune sweep (VERDICT item 2): crossed geometry grid at 500K + 1M
#    confirms; checkpoints tpu_tune_report.json after every cell.
if [ "$only" = all ] || [ "$only" = tune ]; then
  run_step tune 14400 python -u tools/tpu_tune.py
fi
[ "$only" = tune ] && exit 0

# 5. k=100 on chip (VERDICT item 4): bench at the reference's canonical k.
if [ "$only" = all ] || [ "$only" = k100 ]; then
  run_step bench_1m_k100 2400 env BENCH_K=100 BENCH_BUDGET_S=1800 \
      python bench.py
fi

# 6. Re-bench 1M/k=8 with the tune winner (read tpu_tune_report.json by
#    hand and export BENCH_BUCKET_SIZE/BENCH_POINT_GROUP/LSK_CHUNK_LANES
#    before invoking: bash round5/chip_session.sh best).
if [ "$only" = best ]; then
  run_step bench_best 2400 env BENCH_BUDGET_S=1800 python bench.py
fi

log "agenda complete"
