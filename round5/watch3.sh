#!/bin/bash
# Retry TPU contact; on success run agenda3 once, then KEEP WATCHING
# (windows recur — later contact re-runs any step whose .out lacks a
# TPU result is manual; this watcher only fires the agenda once).
LOCK=/root/repo/round5/.watch3.lock
exec 9>"$LOCK"
flock -n 9 || { echo "another watcher holds $LOCK" >&2; exit 1; }
LOG=/root/repo/round5/tunnel_watch.log
echo "watch3 start $(date -u +%FT%TZ)" >> $LOG
while true; do
  timeout 150 python -c "
import sys, time, jax
t0=time.time()
ds = jax.devices()
print('CONTACT', round(time.time()-t0,1), [str(d) for d in ds],
      ds[0].device_kind)
sys.exit(0 if ds and ds[0].platform != 'cpu' else 2)
" >> $LOG 2>&1
  rc=$?
  echo "attempt rc=$rc $(date -u +%FT%TZ)" >> $LOG
  if [ $rc -eq 0 ]; then
    echo "TUNNEL UP -> agenda3 $(date -u +%FT%TZ)" >> $LOG
    bash /root/repo/round5/agenda3.sh >> $LOG 2>&1
    echo "agenda3 exited $(date -u +%FT%TZ)" >> $LOG
    exit 0
  fi
  sleep 20
done
