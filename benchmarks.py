"""BASELINE benchmark suite — instruments the configs of BASELINE.json.

The reference publishes no numbers (BASELINE.md), so this suite ESTABLISHES
the baseline: per config it reports kNN queries/sec and the cross-shard
exchange bandwidth derived from the phase timers (obs/timers.py).

Each config runs in its own subprocess with its own device environment:
single-chip configs use the real TPU when reachable; multi-shard configs use
the virtual-device CPU mesh (this container exposes ONE real chip — the
multi-chip path is validated for correctness/compilation there and measured
for real on a pod). Sizes scale down automatically off-TPU; results are
labeled with platform + actual size so nothing is presented as something it
is not.

    python benchmarks.py            # quick sizes
    python benchmarks.py --full     # BASELINE.json sizes where feasible

Writes benchmarks_report.json and prints one JSON line per config.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

# report lives next to this script, not the cwd — the driver may invoke
# benchmarks.py from anywhere, and the --only merge must find the prior
# report it protects
REPORT_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "benchmarks_report.json")

_CHILD = r"""
import json, os, sys, time
import numpy as np

spec = json.loads(sys.argv[1])

import jax  # noqa: E402  (after env is set by parent)

from mpi_cuda_largescaleknn_tpu.core.config import KnnConfig
from mpi_cuda_largescaleknn_tpu.models.prepartitioned import PrePartitionedKNN
from mpi_cuda_largescaleknn_tpu.models.unordered import UnorderedKNN
from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh

n, k, shards = spec["n"], spec["k"], spec["shards"]
rng = np.random.default_rng(11)
pts = rng.random((n, 3)).astype(np.float32)
cfg = KnnConfig(k=k, engine=spec.get("engine", "auto"),
                query_chunk=spec.get("query_chunk", 0),
                # 0 = engine-aware auto (ring.resolve_bucket_size)
                bucket_size=spec.get("bucket_size", 0))
mesh = get_mesh(shards)

extra = {}
if spec["pipeline"] == "unordered":
    model = UnorderedKNN(cfg, mesh=mesh)
    model.run(pts)                        # compile warmup
    model.timers.phases.clear()
    t0 = time.perf_counter()
    out = model.run(pts)
    dt = time.perf_counter() - t0
    assert out.shape == (n,)
else:
    pts = pts[np.argsort(pts[:, 0], kind="stable")]
    bounds = [(n * r // shards, n * (r + 1) // shards) for r in range(shards)]
    parts = [pts[b:e] for b, e in bounds]
    model = PrePartitionedKNN(cfg, mesh=mesh)
    model.run(parts)
    model.timers.phases.clear()
    t0 = time.perf_counter()
    outs = model.run(parts)
    dt = time.perf_counter() - t0
    assert sum(len(o) for o in outs) == n
    # measured rounds-to-exit vs schedule optima: a rank needs peer j iff
    # box_dist(box_i, box_j) < its worst k-th distance. The reference's
    # nearest-first matching (prePartitionedDataVariant.cu:304-322) moves
    # one tree per rank per round -> best possible 1 + max_i(#needed).
    # Our bidirectional ring (parallel/demand.py) delivers two trees per
    # round -> bound 1 + ceil(max_i(#needed)/2). PARITY.md discusses the
    # trade; round-4 measurements motivated the counter-rotation.
    los = np.array([p.min(0) for p in parts]); his = np.array([p.max(0) for p in parts])
    # box-box distance: max(0, lo_i - hi_j, lo_j - hi_i) per dim, 2-norm
    d = np.maximum(0.0, np.maximum(los[:, None, :] - his[None, :, :],
                                   los[None, :, :] - his[:, None, :]))
    boxdist = np.sqrt((d ** 2).sum(-1))
    worst = np.array([o.max() for o in outs])
    needed = ((boxdist < worst[:, None]).sum(1) - 1)  # excl. self
    extra["demand_rounds_measured"] = (model.last_stats or {}).get("rounds")
    extra["demand_rounds_reference_best"] = int(needed.max()) + 1
    # exact bidir-ring optimum: a needed peer at ring offset o arrives in
    # round o (two counter-rotating copies), so the schedule cannot beat
    # 1 + max over needed (i, j) of min(|i-j| mod R, |j-i| mod R)
    idx = np.arange(shards)
    offs = np.minimum((idx[:, None] - idx[None, :]) % shards,
                      (idx[None, :] - idx[:, None]) % shards)
    need_mask = (boxdist < worst[:, None]) & ~np.eye(shards, dtype=bool)
    extra["demand_rounds_bidir_bound"] = (
        int((offs * need_mask).max()) + 1 if need_mask.any() else 1)
    extra["needed_peers_per_shard"] = needed.tolist()
    # per-direction rotation gating (parallel/demand.py): fraction of the
    # ungated scheme's exchange bytes (2 rotations/round/device) not moved
    st = model.last_stats or {}
    rot = np.asarray(st.get("rotations_run") or [])
    # chunked runs SUM rotations over chunks but report 'rounds' as the
    # per-chunk max — the ungated-bytes denominator must sum rounds too
    rounds_den = (sum(st["rounds_per_chunk"]) if st.get("rounds_per_chunk")
                  else st.get("rounds") or 0)
    if rot.size and rounds_den:
        extra["exchange_rotations_run_per_device"] = rot.tolist()
        extra["exchange_bytes_saved_frac"] = round(
            1.0 - float(rot.mean()) / (2 * rounds_den), 3)

if shards > 1:
    # MEASURED per-round rotation bandwidth (ppermute minus no-comm
    # control, parallel/ring.py) next to the analytic phase-level figure
    from mpi_cuda_largescaleknn_tpu.parallel.ring import (
        measure_exchange_bandwidth,
    )
    extra["exchange_measured"] = measure_exchange_bandwidth(
        mesh, -(-n // shards), bucket_size=cfg.bucket_size,
        engine=cfg.engine)

rep = model.timers.report()
ring = rep.get("ring") or rep.get("demand_ring") or {}
from mpi_cuda_largescaleknn_tpu.obs.cost import cost_report
pair_evals = (getattr(model, "last_stats", None) or {}).get("pair_evals", 0)
cr = (cost_report(pair_evals, ring.get("seconds", dt),
                  jax.devices()[0].platform) if pair_evals else {})
print("RESULT " + json.dumps({
    "config": spec["name"],
    "pipeline": spec["pipeline"],
    "n_points": n, "k": k, "shards": shards,
    "scaled_down": spec.get("scaled", False),
    "platform": jax.devices()[0].platform,
    "queries_per_sec": round(n / dt, 1),
    "seconds": round(dt, 3),
    "device_seconds": ring.get("seconds"),
    # headline exchange figure: the MEASURED per-link rotation bandwidth
    # (parallel/ring.py measure_exchange_bandwidth) when available; the
    # phase-timer analytic figure only as a fallback (it reads 0.0 when the
    # phase timers attribute no bytes to the ring phase)
    "exchange_GB_per_sec": (
        extra.get("exchange_measured", {}).get(
            "exchange_GB_per_sec_per_link") or ring.get("GB/s", 0.0)),
    "stats": getattr(model, "last_stats", None),
    **cr, **extra,
}), flush=True)
"""


def _tpu_ok(timeout_s: float | None = None) -> bool:
    # first contact through the single-client tunnel alone can take
    # 60-240+ s — a short probe here silently demotes every config to the
    # CPU fallback (the round-1 failure mode)
    if timeout_s is None:
        timeout_s = float(os.environ.get("BENCHSUITE_PROBE_S", 300))
    probe = ("import jax; d=jax.devices(); "
             "import sys; sys.exit(0 if d and d[0].platform != 'cpu' else 1)")
    try:
        return subprocess.run([sys.executable, "-c", probe],
                              timeout=timeout_s,
                              capture_output=True).returncode == 0
    except subprocess.TimeoutExpired:
        return False


def main() -> int:
    full = "--full" in sys.argv
    # validate flags BEFORE the TPU probe: a usage error must fail in
    # milliseconds, not after dialing the (single-client) tunnel
    only = None
    if "--only" in sys.argv:
        idx = sys.argv.index("--only") + 1
        if idx >= len(sys.argv) or sys.argv[idx].startswith("-"):
            sys.stderr.write("usage: benchmarks.py [--full] --only <name>\n")
            return 2
        only = sys.argv[idx]
    tpu = _tpu_ok()

    def env_for(shards: int, use_tpu: bool):
        env = dict(os.environ)
        if not use_tpu:
            env["JAX_PLATFORMS"] = "cpu"
            env.pop("PALLAS_AXON_POOL_IPS", None)
            flags = env.get("XLA_FLAGS", "")
            env["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count={shards}"
            ).strip()
        return env

    # (name, pipeline, (shards, n, k) full, (shards, n, k) quick, extras)
    # quick mode scales N (and nothing else) down so the CPU smoke run
    # finishes in minutes — k and shard count stay AT SPEC so the
    # k-scaling cost center (the width-2k merge, ops/candidates.py) and the
    # 64-shard round-count behavior are really exercised; results carry the
    # actual parameters so scaled runs cannot masquerade as spec runs
    configs = [
        ("unordered_1dev_k8", "unordered",
         (1, 1_000_000, 8), (1, 200_000 if tpu else 20_000, 8), {}),
        # k-scaling curve on one device (TPU-eligible): same N, k swept —
        # the merge cost center scales with k (width-2k sorted rows)
        ("unordered_1dev_k32", "unordered",
         (1, 1_000_000, 32), (1, 100_000 if tpu else 20_000, 32), {}),
        ("unordered_1dev_k100", "unordered",
         (1, 1_000_000, 100), (1, 100_000 if tpu else 10_000, 100), {}),
        ("unordered_8shard_k100", "unordered",
         (8, 400_000, 100), (8, 8_000, 100), {}),
        ("prepartitioned_8shard_k100", "prepartitioned",
         (8, 400_000, 100), (8, 8_000, 100), {}),
        ("prepartitioned_64shard_k500_overlap", "prepartitioned",
         (64, 256_000, 500), (64, 32_000, 500), {"bucket_size": 128}),
        ("unordered_streaming_chunked_k100", "unordered",
         (8, 400_000, 100), (8, 8_000, 100), {"query_chunk": 1024}),
    ]

    config_order = [c[0] for c in configs]
    if only is not None:
        # targeted re-run (e.g. one config crashed under a loaded host):
        # substring filter on config name; rows MERGE into the existing
        # report, replacing that config's old row, instead of clobbering
        # the other configs' results
        configs = [c for c in configs if only in c[0]]
        if not configs:
            sys.stderr.write(f"no config matches {only!r}\n")
            return 2

    results = []
    for name, pipeline, full_snk, quick_snk, extras in configs:
        shards, n, k = full_snk if full else quick_snk
        use_tpu = tpu and shards == 1
        spec = {"name": name, "pipeline": pipeline, "shards": shards,
                "n": n, "k": k, "scaled": not full, **extras}
        try:
            r = subprocess.run(
                [sys.executable, "-c", _CHILD, json.dumps(spec)],
                timeout=float(os.environ.get("BENCHSUITE_TIMEOUT_S", 1200)),
                capture_output=True, text=True,
                env=env_for(shards, use_tpu))
        except subprocess.TimeoutExpired:
            results.append({"config": name, "error": "timeout"})
            print(json.dumps(results[-1]), flush=True)
            continue
        line = next((ln for ln in r.stdout.splitlines()
                     if ln.startswith("RESULT ")), None)
        if r.returncode != 0 or line is None:
            results.append({"config": name,
                            "error": (r.stderr or "no output")[-500:]})
        else:
            results.append(json.loads(line[len("RESULT "):]))
        print(json.dumps(results[-1]), flush=True)

    if only is not None:
        try:
            with open(REPORT_PATH) as f:
                prior = json.load(f)
            prior_ok = {r.get("config"): r for r in prior.get("results", [])
                        if "error" not in r}
            # a failed re-run must not clobber a prior good measurement
            # (e.g. retrying on a weaker host): keep the old row then
            results = [r if "error" not in r
                       else prior_ok.get(r.get("config"), r)
                       for r in results]
            rerun = {r.get("config") for r in results}
            results = [r for r in prior.get("results", [])
                       if r.get("config") not in rerun] + results
            # keep the committed report's canonical row order (stable
            # human diffs); unknown configs sink to the end
            results.sort(key=lambda r: (
                config_order.index(r["config"])
                if r.get("config") in config_order else len(config_order)))
            # top-level flags describe ALL rows: after a mixed-provenance
            # merge they can only be trusted when both runs agree —
            # disagreement nulls the flag (falsy for naive consumers; the
            # per-row scaled_down/platform fields stay authoritative)
            if prior.get("full") != full:
                full = None
            if prior.get("tpu_available") != tpu:
                tpu = None
        except (OSError, ValueError):
            pass  # no prior report: write just the re-run rows
    with open(REPORT_PATH, "w") as f:
        json.dump({"full": full, "tpu_available": tpu,
                   "results": results}, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
