import numpy as np
import pytest

from .oracle import random_points

from mpi_cuda_largescaleknn_tpu.ops.build_tree import build_tree, left_subtree_size

import jax.numpy as jnp




def test_left_subtree_size_small_values():
    # hand-checked values for complete left-balanced trees
    want = {1: 0, 2: 1, 3: 1, 4: 2, 5: 3, 6: 3, 7: 3, 8: 4, 9: 5, 10: 6,
            11: 7, 12: 7, 15: 7, 16: 8, 31: 15}
    got = np.array(left_subtree_size(jnp.array(sorted(want))))
    np.testing.assert_array_equal(got, [want[n] for n in sorted(want)])


def _check_kd_property(tree, node=0, depth=0):
    """Recursive host-side check: every node's left subtree is <= it and right
    subtree >= it along the node's round-robin split dimension."""
    n = len(tree)
    if node >= n:
        return
    dim = depth % 3

    def subtree_nodes(root):
        out, stack = [], [root]
        while stack:
            i = stack.pop()
            if i < n:
                out.append(i)
                stack += [2 * i + 1, 2 * i + 2]
        return out

    for c in subtree_nodes(2 * node + 1):
        assert tree[c, dim] <= tree[node, dim], (node, c, dim)
    for c in subtree_nodes(2 * node + 2):
        assert tree[c, dim] >= tree[node, dim], (node, c, dim)
    _check_kd_property(tree, 2 * node + 1, depth + 1)
    _check_kd_property(tree, 2 * node + 2, depth + 1)


@pytest.mark.parametrize("n", [1, 2, 3, 7, 8, 100, 255, 256, 257, 1000])
def test_tree_is_permutation_and_kd_ordered(n):
    pts = random_points(n, seed=n)
    tree, tree_ids = build_tree(pts)
    tree = np.array(tree)
    tree_ids = np.array(tree_ids)
    # permutation of the input, ids consistent
    assert sorted(tree_ids.tolist()) == list(range(n))
    np.testing.assert_array_equal(tree, pts[tree_ids])
    _check_kd_property(tree)


def test_duplicate_coordinates():
    rng = np.random.default_rng(1)
    pts = rng.integers(0, 3, (64, 3)).astype(np.float32)  # heavy ties
    tree, tree_ids = build_tree(pts)
    assert sorted(np.array(tree_ids).tolist()) == list(range(64))
    _check_kd_property(np.array(tree))


def test_input_order_invariance_of_structure():
    # permuting the input must not change the set of points at each node when
    # coordinates are unique (left-balanced layout is canonical up to ties)
    pts = random_points(200, seed=7)
    tree1, _ = build_tree(pts)
    perm = np.random.default_rng(2).permutation(200)
    tree2, _ = build_tree(pts[perm])
    np.testing.assert_array_equal(np.array(tree1), np.array(tree2))
