"""Device-side cross-shard top-k merge: the ISSUE-3 exactness contract.

The device merge (ops/candidates.py ``tree_merge_candidates`` + the
engine's / chunked driver's ``merge="device"`` programs) must be
BIT-IDENTICAL to the host merge — distances, neighbor indices, and
equal-distance tie-breaks — across shard counts, ragged/padded batches,
and duplicate-heavy point sets. These tests are the proof; the host merge's
argpartition rewrite is held to the same standard against the stable
argsort it replaced.
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

from mpi_cuda_largescaleknn_tpu.serve.engine import (
    ResidentKnnEngine,
    _merge_shard_candidates,
)
from tests.oracle import assert_dist_equal, kth_nn_dist, random_points

K = 4


def _dup_points(n, seed):
    """Point set with heavy exact duplicates spread across slab shards:
    equal-distance candidates with DIFFERENT global ids exist for nearly
    every query, so any tie-discipline divergence between merge placements
    shows up as a neighbor-id mismatch."""
    base = random_points(max(n // 4, 8), seed=seed)
    reps = -(-n // len(base))
    return np.tile(base, (reps, 1))[:n].copy()


def _engine_pair(points, r, **kw):
    from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh

    mesh = get_mesh(r)
    args = dict(engine="tiled", bucket_size=32, max_batch=32, min_batch=16)
    args.update(kw)
    return (ResidentKnnEngine(points, K, mesh=mesh, merge="host", **args),
            ResidentKnnEngine(points, K, mesh=mesh, merge="device", **args))


class TestDeviceMergeEqualsHostMerge:
    @pytest.mark.parametrize("r", [1, 2, 4, 8])
    def test_property_across_shard_counts(self, r):
        """The acceptance bar: device merge == host merge bit-for-bit —
        distances, neighbor ids, and tie order — at R in {1, 2, 4, 8},
        with duplicate points forcing cross-shard distance ties and ragged
        batch sizes forcing padded sentinel query rows."""
        points = _dup_points(600, seed=r)
        host, dev = _engine_pair(points, r)
        assert host.merge_mode == "host" and dev.merge_mode == "device"
        for n in (1, 5, 16, 17, 32):  # ragged sizes pad up to 16/32 buckets
            q = random_points(n, seed=100 * r + n)
            q[: n // 2] = points[: n // 2]  # query ON duplicated points:
            dh, nh = host.query(q)         # distance-0 ties included
            dd, nd = dev.query(q)
            np.testing.assert_array_equal(dh, dd)
            np.testing.assert_array_equal(nh, nd)
            assert_dist_equal(dd, kth_nn_dist(q, points, K))

    def test_bruteforce_engine_matches_too(self):
        points = _dup_points(300, seed=9)
        host, dev = _engine_pair(points, 8, engine="bruteforce")
        q = random_points(20, seed=5)
        dh, nh = host.query(q)
        dd, nd = dev.query(q)
        np.testing.assert_array_equal(dh, dd)
        np.testing.assert_array_equal(nh, nd)

    def test_max_radius_underfull_rows_match(self):
        """Under-full heaps (max_radius cutoff): the untouched r^2 / -1
        slots tie across every shard — the all-sentinel tie case."""
        points = random_points(400, seed=3)
        host, dev = _engine_pair(points, 4, max_radius=0.05)
        q = random_points(24, seed=7)
        dh, nh = host.query(q)
        dd, nd = dev.query(q)
        np.testing.assert_array_equal(dh, dd)
        np.testing.assert_array_equal(nh, nd)

    def test_fetch_bytes_shrink_by_shard_count(self):
        """complete() under device merge fetches one final [Q] + [Q, k]
        instead of R x [Q, k] partial pairs: >= R x fewer bytes."""
        points = random_points(500, seed=1)
        host, dev = _engine_pair(points, 8)
        q = random_points(32, seed=2)
        host.query(q)
        dev.query(q)
        hb = host.stats()["fetch_bytes"]
        db = dev.stats()["fetch_bytes"]
        assert hb >= 8 * db, (hb, db)
        assert host.stats()["result_rows"] == dev.stats()["result_rows"] == 32

    def test_compile_count_discipline_per_merge_mode(self):
        """Device-merge programs live in their own AOT shape buckets: warmup
        compiles exactly one program per bucket, traffic across every
        ragged size adds zero."""
        points = random_points(400, seed=4)
        _, dev = _engine_pair(points, 8)
        dev.warmup()
        warm = dev.compile_count
        assert warm == len(dev.shape_buckets)
        for n in (1, 3, 16, 17, 31, 32):
            dev.query(random_points(n, seed=n))
        assert dev.compile_count == warm

    def test_min_batch_bumped_to_tile_mesh(self):
        """Device merge slices the final result 1/R per device, so shape
        buckets must be >= num_shards; the engine bumps min_batch."""
        from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh

        eng = ResidentKnnEngine(random_points(200, seed=1), K,
                                mesh=get_mesh(8), engine="tiled",
                                bucket_size=32, max_batch=32, min_batch=2,
                                merge="device")
        assert eng.shape_buckets[0] >= 8


class TestResolveMerge:
    def test_auto_prefers_device_on_pow2(self):
        from mpi_cuda_largescaleknn_tpu.parallel.ring import resolve_merge

        assert resolve_merge("auto", 8) == "device"
        assert resolve_merge("auto", 1) == "device"
        assert resolve_merge("auto", 6) == "host"
        assert resolve_merge("host", 8) == "host"
        with pytest.raises(ValueError, match="power-of-two"):
            resolve_merge("device", 6)
        with pytest.raises(ValueError, match="unknown merge"):
            resolve_merge("gpu", 8)

    def test_engine_auto_on_non_pow2_mesh_falls_back(self):
        from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh

        eng = ResidentKnnEngine(random_points(200, seed=1), K,
                                mesh=get_mesh(3), engine="tiled",
                                bucket_size=32, max_batch=16, min_batch=16)
        assert eng.merge_mode == "host"
        d, _ = eng.query(random_points(6, seed=2))
        assert d.shape == (6,)

    def test_auto_non_pow2_falls_back_with_logged_warning(self, caplog):
        """Satellite: ``auto`` on a non-power-of-two (pod) mesh falls back
        to the host merge with a LOGGED warning — never a hard startup
        failure — while explicit ``device`` still raises."""
        import logging

        from mpi_cuda_largescaleknn_tpu.parallel.ring import resolve_merge

        with caplog.at_level(logging.WARNING,
                             logger="mpi_cuda_largescaleknn_tpu"):
            assert resolve_merge("auto", 6) == "host"
        assert any("not a power of two" in r.message for r in caplog.records)
        # R=1 "falls back" trivially to device and must not warn
        caplog.clear()
        with caplog.at_level(logging.WARNING,
                             logger="mpi_cuda_largescaleknn_tpu"):
            assert resolve_merge("auto", 1) == "device"
        assert not caplog.records

    def test_chunked_auto_on_multi_host_keeps_device(self, monkeypatch):
        """merge='auto' under multi-host now resolves to the device merge
        on a power-of-two global mesh (the raise was lifted) and falls
        through to the multi-host INPUT validation, not a merge error."""
        import jax

        from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
        from mpi_cuda_largescaleknn_tpu.parallel.ring import ring_knn_chunked

        monkeypatch.setattr(jax, "process_count", lambda: 2)
        with pytest.raises(ValueError, match="global sharded"):
            ring_knn_chunked(np.zeros((64, 3), np.float32),
                             np.zeros(64, np.int32), K, get_mesh(8),
                             chunk_rows=8, merge="auto")


class TestTreeMergeKernel:
    def test_tree_merge_equals_host_merge_on_synthetic_ties(self):
        """tree_merge_candidates under shard_map vs the host stable merge
        on hand-built per-shard candidate rows riddled with cross-shard
        ties: the reduction must pick the SAME winners in the SAME order
        (earlier shard, then earlier slot, at equal distance)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from mpi_cuda_largescaleknn_tpu.core.types import CandidateState
        from mpi_cuda_largescaleknn_tpu.ops.candidates import (
            tree_merge_candidates,
        )
        from mpi_cuda_largescaleknn_tpu.parallel.mesh import AXIS, get_mesh

        r, q, k = 8, 16, 4
        rng = np.random.default_rng(0)
        vals = rng.choice(
            np.float32([0.0, 0.25, 0.25, 0.5, 1.0, np.inf]),
            size=(r * q, k))
        d2 = np.sort(vals, axis=1)
        idx = rng.integers(0, 99, size=(r * q, k)).astype(np.int32)
        want_d, want_idx = _merge_shard_candidates(
            d2.copy(), idx.copy(), r, q, k)

        mesh = get_mesh(r)
        spec = P(AXIS)

        def body(d2_l, idx_l):
            st = tree_merge_candidates(CandidateState(d2_l, idx_l), AXIS, r)
            return st.dist2, st.idx

        got_d2, got_idx = jax.jit(jax.shard_map(
            body, mesh=mesh, in_specs=(spec, spec), out_specs=(spec, spec)))(
            jax.device_put(d2, NamedSharding(mesh, spec)),
            jax.device_put(idx, NamedSharding(mesh, spec)))
        # every device must hold the identical global top-k (all-reduce)
        got_d2 = np.asarray(got_d2).reshape(r, q, k)
        got_idx = np.asarray(got_idx).reshape(r, q, k)
        for dev in range(r):
            np.testing.assert_array_equal(np.sqrt(got_d2[dev][:, k - 1]),
                                          want_d)
            np.testing.assert_array_equal(got_idx[dev], want_idx)

    def test_non_pow2_raises(self):
        from mpi_cuda_largescaleknn_tpu.core.types import CandidateState
        from mpi_cuda_largescaleknn_tpu.ops.candidates import (
            tree_merge_candidates,
        )

        with pytest.raises(ValueError, match="power-of-two"):
            tree_merge_candidates(CandidateState(None, None), "shards", 6)

    @pytest.mark.parametrize("via", ["a2a", "tree"])
    def test_device_merge_final_variants_equal_host(self, via):
        """Both reductions behind device_merge_final — the all_to_all +
        top_k reduce-scatter and the ppermute tree — must reproduce the
        host merge bit-for-bit, ties included."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from mpi_cuda_largescaleknn_tpu.core.types import CandidateState
        from mpi_cuda_largescaleknn_tpu.parallel.mesh import AXIS, get_mesh
        from mpi_cuda_largescaleknn_tpu.parallel.ring import (
            device_merge_final,
        )

        r, q, k = 4, 12, 5
        rng = np.random.default_rng(7)
        vals = rng.choice(
            np.float32([0.0, 0.5, 0.5, 0.5, 2.0, np.inf]), size=(r * q, k))
        d2 = np.sort(vals, axis=1)
        idx = rng.integers(0, 77, size=(r * q, k)).astype(np.int32)
        want_d, want_idx = _merge_shard_candidates(
            d2.copy(), idx.copy(), r, q, k)

        mesh = get_mesh(r)
        spec = P(AXIS)

        def body(d2_l, idx_l):
            dd, _d2m, ii = device_merge_final(
                CandidateState(d2_l, idx_l), r, via=via)
            return dd, ii

        got_d, got_idx = jax.jit(jax.shard_map(
            body, mesh=mesh, in_specs=(spec, spec), out_specs=(spec, spec)))(
            jax.device_put(d2, NamedSharding(mesh, spec)),
            jax.device_put(idx, NamedSharding(mesh, spec)))
        np.testing.assert_array_equal(np.asarray(got_d), want_d)
        np.testing.assert_array_equal(np.asarray(got_idx), want_idx)


class TestGlobalAxisTreeMerge:
    """The pod level consumes tree_merge_candidates / device_merge_final
    UNCHANGED on the global mesh axis (ROADMAP multi-host serving): these
    cases stand a shard block in for each host — duplicate points spanning
    "hosts" force cross-host equal-distance ties with different global
    ids, and a max_radius cutoff leaves ragged rows whose untouched
    (r^2, -1) pad slots must tie-break exactly like the single-host
    canonical order (_merge_shard_candidates)."""

    @staticmethod
    def _shard_states(r, q, k, seed, radius=None):
        """Real per-"host" candidate rows from a duplicate-heavy point set:
        host s owns slab s of a point set where every point appears 4x
        across slabs; per-host rows are the canonical (dist2, id)
        ascending top-k of that host's slab, radius-bounded so ragged rows
        keep their (radius^2, -1) init slots."""
        rng = np.random.default_rng(seed)
        base = rng.random((16, 3)).astype(np.float32)
        pts = np.tile(base, (4, 1))  # every point duplicated across slabs
        ids = np.arange(len(pts), dtype=np.int32)
        queries = pts[rng.integers(0, len(pts), q)]  # queries ON dup points
        d2 = ((queries[:, None, :].astype(np.float32)
               - pts[None]) ** 2).sum(-1).astype(np.float32)
        cut = (np.float32(radius) ** 2 if radius is not None
               else np.float32(np.inf))
        out_d2 = np.full((r * q, k), cut, np.float32)
        out_idx = np.full((r * q, k), -1, np.int32)
        for s, cols in enumerate(np.array_split(np.arange(len(pts)), r)):
            dd, ii = d2[:, cols], ids[cols]
            order = np.argsort(dd, axis=1, kind="stable")[:, :k]
            vals = np.take_along_axis(dd, order, axis=1)
            keep = vals < cut  # strict <, ascending rows: a prefix mask
            out_d2[s * q:(s + 1) * q] = np.where(keep, vals, cut)
            out_idx[s * q:(s + 1) * q] = np.where(keep, ii[order], -1)
        return out_d2, out_idx

    @pytest.mark.parametrize("r", [2, 4])
    @pytest.mark.parametrize("radius", [None, 0.25])
    def test_tree_all_reduce_matches_canonical_order(self, r, radius):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from mpi_cuda_largescaleknn_tpu.core.types import CandidateState
        from mpi_cuda_largescaleknn_tpu.ops.candidates import (
            tree_merge_candidates,
        )
        from mpi_cuda_largescaleknn_tpu.parallel.mesh import AXIS, get_mesh

        q, k = 24, K
        d2, idx = self._shard_states(r, q, k, seed=40 + r, radius=radius)
        want_d, want_idx = _merge_shard_candidates(
            d2.copy(), idx.copy(), r, q, k)
        mesh = get_mesh(r)
        spec = P(AXIS)

        def body(d2_l, idx_l):
            st = tree_merge_candidates(CandidateState(d2_l, idx_l), AXIS, r)
            return st.dist2, st.idx

        got_d2, got_idx = jax.jit(jax.shard_map(
            body, mesh=mesh, in_specs=(spec, spec), out_specs=(spec, spec)))(
            jax.device_put(d2, NamedSharding(mesh, spec)),
            jax.device_put(idx, NamedSharding(mesh, spec)))
        got_d2 = np.asarray(got_d2).reshape(r, q, k)
        got_idx = np.asarray(got_idx).reshape(r, q, k)
        for host in range(r):  # all-reduce: every "host" holds the answer
            np.testing.assert_array_equal(
                np.sqrt(got_d2[host][:, k - 1]), want_d)
            np.testing.assert_array_equal(got_idx[host], want_idx)

    @pytest.mark.parametrize("r", [2, 4])
    @pytest.mark.parametrize("via", ["a2a", "tree"])
    def test_final_slices_match_canonical_order(self, r, via):
        """device_merge_final on the same "pod" axis: each host's 1/R row
        slice of the final answer — the bytes the serving front end
        assembles — equals the canonical merge, ties and pads included."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from mpi_cuda_largescaleknn_tpu.core.types import CandidateState
        from mpi_cuda_largescaleknn_tpu.parallel.mesh import AXIS, get_mesh
        from mpi_cuda_largescaleknn_tpu.parallel.ring import (
            device_merge_final,
        )

        q, k = 16, K
        d2, idx = self._shard_states(r, q, k, seed=60 + r, radius=0.3)
        want_d, want_idx = _merge_shard_candidates(
            d2.copy(), idx.copy(), r, q, k)
        mesh = get_mesh(r)
        spec = P(AXIS)

        def body(d2_l, idx_l):
            dd, _d2m, ii = device_merge_final(
                CandidateState(d2_l, idx_l), r, via=via)
            return dd, ii

        got_d, got_idx = jax.jit(jax.shard_map(
            body, mesh=mesh, in_specs=(spec, spec), out_specs=(spec, spec)))(
            jax.device_put(d2, NamedSharding(mesh, spec)),
            jax.device_put(idx, NamedSharding(mesh, spec)))
        np.testing.assert_array_equal(np.asarray(got_d), want_d)
        np.testing.assert_array_equal(np.asarray(got_idx), want_idx)


class TestHostMergeMicroFix:
    """The argpartition rewrite of _merge_shard_candidates must be
    output-identical to the stable full argsort it replaced."""

    @staticmethod
    def _reference(d2, idx, r, qpad, k):
        d2 = d2.reshape(r, qpad, k).transpose(1, 0, 2).reshape(qpad, -1)
        idx = idx.reshape(r, qpad, k).transpose(1, 0, 2).reshape(qpad, -1)
        order = np.argsort(d2, axis=1, kind="stable")[:, :k]
        return (np.sqrt(np.take_along_axis(d2, order, axis=1)[:, k - 1]),
                np.take_along_axis(idx, order, axis=1))

    def test_matches_stable_argsort_on_adversarial_ties(self):
        rng = np.random.default_rng(1)
        for _ in range(60):
            r = int(rng.choice([1, 2, 3, 4, 8]))
            k = int(rng.integers(1, 9))
            qpad = int(rng.integers(1, 24))
            vals = rng.choice(
                np.float32([0.0, 0.125, 0.125, 0.125, 0.5, np.inf]),
                size=(r * qpad, k))
            d2 = np.sort(vals, axis=1)  # per-shard rows arrive sorted
            idx = rng.integers(-1, 40, size=(r * qpad, k)).astype(np.int32)
            got = _merge_shard_candidates(d2.copy(), idx.copy(), r, qpad, k)
            want = self._reference(d2, idx, r, qpad, k)
            np.testing.assert_array_equal(got[0], want[0])
            np.testing.assert_array_equal(got[1], want[1])

    def test_all_inf_rows(self):
        r, qpad, k = 4, 3, 5
        d2 = np.full((r * qpad, k), np.inf, np.float32)
        idx = np.full((r * qpad, k), -1, np.int32)
        d, nbrs = _merge_shard_candidates(d2, idx, r, qpad, k)
        assert np.all(np.isinf(d))
        assert np.all(nbrs == -1)


class TestChunkedDeviceMerge:
    """ring_knn_chunked(merge="device"): the replicate-traverse-merge chunk
    path reuses the same reduction and must match the ring bit-for-bit."""

    @staticmethod
    def _sharded(points, r):
        from mpi_cuda_largescaleknn_tpu.models.sharding import (
            pad_and_flatten,
            slab_bounds,
        )

        bounds = slab_bounds(len(points), r)
        shards = [points[b:e] for b, e in bounds]
        flat, ids, _c, _n = pad_and_flatten(
            shards, id_bases=[b for b, _ in bounds])
        return flat, ids

    @pytest.mark.parametrize("engine", ["tiled", "bruteforce"])
    def test_parity_with_ring_path(self, engine):
        """Tie-free data: the two chunk strategies agree bit-for-bit on
        everything — distances, candidate distances, AND neighbor ids."""
        from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
        from mpi_cuda_largescaleknn_tpu.parallel.ring import ring_knn_chunked

        points = random_points(512, seed=11)
        mesh = get_mesh(8)
        flat, ids = self._sharded(points, 8)
        kw = dict(k=K, mesh=mesh, chunk_rows=16, engine=engine,
                  bucket_size=32, return_candidates=True)
        dh, ch = ring_knn_chunked(flat, ids, merge="host", **kw)
        dd, cd = ring_knn_chunked(flat, ids, merge="device", **kw)
        np.testing.assert_array_equal(dh, dd)
        np.testing.assert_array_equal(np.asarray(ch.dist2),
                                      np.asarray(cd.dist2))
        np.testing.assert_array_equal(np.asarray(ch.idx),
                                      np.asarray(cd.idx))

    def test_duplicate_points_distances_exact_ids_true(self):
        """Duplicate-heavy data: candidate DISTANCES still match the ring
        bit-for-bit, but equal-distance id ORDER legitimately differs —
        the ring resolves ties in fold-arrival order (own shard first,
        per-device), the device merge in ascending (shard, slot) order,
        the serving engine's discipline. Both id sets must be true
        k-nearest by distance."""
        from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
        from mpi_cuda_largescaleknn_tpu.parallel.ring import ring_knn_chunked
        from tests.oracle import pairwise_dist2_np

        points = _dup_points(512, seed=11)
        mesh = get_mesh(8)
        flat, ids = self._sharded(points, 8)
        kw = dict(k=K, mesh=mesh, chunk_rows=16, engine="tiled",
                  bucket_size=32, return_candidates=True)
        dh, ch = ring_knn_chunked(flat, ids, merge="host", **kw)
        dd, cd = ring_knn_chunked(flat, ids, merge="device", **kw)
        np.testing.assert_array_equal(dh, dd)
        np.testing.assert_array_equal(np.asarray(ch.dist2),
                                      np.asarray(cd.dist2))
        full = pairwise_dist2_np(points, points)
        nbrs = np.asarray(cd.idx)[:len(points)]
        got_d2 = np.sort(full[np.arange(len(points))[:, None], nbrs], axis=1)
        want_d2 = np.sort(full, axis=1)[:, :K]
        np.testing.assert_allclose(got_d2, want_d2, rtol=5e-7)

    def test_checkpoint_resume_under_device_merge(self, tmp_path):
        from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
        from mpi_cuda_largescaleknn_tpu.parallel.ring import ring_knn_chunked

        points = random_points(512, seed=13)
        mesh = get_mesh(8)
        flat, ids = self._sharded(points, 8)
        kw = dict(k=K, mesh=mesh, chunk_rows=16, engine="tiled",
                  bucket_size=32, merge="device",
                  checkpoint_dir=str(tmp_path))
        ring_knn_chunked(flat, ids, max_chunks=2, **kw)
        got = ring_knn_chunked(flat, ids, **kw)
        want = ring_knn_chunked(flat, ids, k=K, mesh=mesh, chunk_rows=16,
                                engine="tiled", bucket_size=32)
        np.testing.assert_array_equal(got, want)

    def test_multi_host_device_merge_validates_inputs(self, monkeypatch):
        """merge='device' is no longer rejected multi-host (the pod-mesh
        lift); like every multi-host chunked run it requires global
        sharded jax.Arrays. The real 2-process byte-identity proof is
        tests/test_multihost.py
        test_two_process_chunked_device_merge_matches_single."""
        import jax

        from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
        from mpi_cuda_largescaleknn_tpu.parallel.ring import ring_knn_chunked

        monkeypatch.setattr(jax, "process_count", lambda: 2)
        with pytest.raises(ValueError, match="global sharded"):
            ring_knn_chunked(np.zeros((64, 3), np.float32),
                             np.zeros(64, np.int32), K, get_mesh(8),
                             chunk_rows=8, merge="device")


class TestServeE2EDeviceMerge:
    """The ISSUE's serving bar: oracle-exact answers through the full HTTP
    stack at merge="device" with pipeline depth 2, recompile-free."""

    @pytest.fixture(scope="class")
    def dev_server(self):
        from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
        from mpi_cuda_largescaleknn_tpu.serve.server import build_server

        points = random_points(1200, seed=21)
        eng = ResidentKnnEngine(points, K, mesh=get_mesh(8), engine="tiled",
                                bucket_size=32, max_batch=128, min_batch=16,
                                merge="device")
        eng.warmup()
        srv = build_server(eng, port=0, max_delay_s=0.002, pipeline_depth=2)
        srv.ready = True
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        yield srv, points
        srv.close()

    @staticmethod
    def _url(srv):
        return f"http://127.0.0.1:{srv.server_address[1]}"

    def test_concurrent_clients_oracle_exact(self, dev_server):
        srv, points = dev_server
        base = self._url(srv)
        results = {}

        def client(i):
            q = random_points(5 + 3 * i, seed=200 + i)
            req = urllib.request.Request(
                base + "/knn",
                data=json.dumps({"queries": q.tolist(),
                                 "neighbors": True}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=60) as resp:
                results[i] = (q, resp.status, json.loads(resp.read()))

        ths = [threading.Thread(target=client, args=(i,)) for i in range(6)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        assert len(results) == 6
        for q, status, resp in results.values():
            assert status == 200
            assert_dist_equal(np.asarray(resp["dists"], np.float32),
                              kth_nn_dist(q, points, K))
            assert len(resp["neighbors"]) == len(q)

    def test_compile_count_parity_and_stats(self, dev_server):
        """All the pipelined device-merge traffic above stayed inside the
        warmed AOT buckets; /stats and /metrics expose the merge mode and
        the fetch accounting."""
        srv, _ = dev_server
        base = self._url(srv)
        stats = json.loads(urllib.request.urlopen(
            base + "/stats", timeout=10).read())
        e = stats["engine"]
        assert e["merge"] == "device"
        assert e["compile_count"] == len(e["shape_buckets"])
        assert e["fetch_bytes"] > 0 and e["result_rows"] > 0
        m = urllib.request.urlopen(base + "/metrics",
                                   timeout=10).read().decode()
        assert "# TYPE knn_fetch_bytes_total counter" in m
        assert "knn_result_rows_total" in m
        assert 'knn_merge_mode{mode="device"} 1' in m
