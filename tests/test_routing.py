"""Shard-local routing: bounds-pruned pod fan-out == replicate-everything.

The routed pod (serve/frontend.py ``RoutedPodFanout`` + per-host
``--routing bounds`` slab engines) must be BITWISE identical — distances
AND neighbor ids, ties included — to one engine over the union of the
hosts' points, which PR-5 proved byte-identical to the replicate-everything
pod. The fixture is adversarial on purpose:

- host 0 owns cluster A (rows 0..294) PLUS five outlier rows (295..299)
  that are exact coordinate copies of host 1's rows 595..599 — so host 0's
  bounding boxes overlap host 1's region (the nearest-bounds wave picks the
  WRONG host for B-region queries, forcing the escalation second wave) and
  distance-0 ties span hosts (any tie-discipline divergence shows up as an
  id mismatch).
- host 1 owns cluster B (rows 300..599), spatially disjoint from A — so
  A-region queries must CERTIFY after one host (the routing win).

Plus bounds-table unit tests (sentinel/empty shards) and the
radius-capped / under-full fold discipline without HTTP in the loop.
"""

from __future__ import annotations

import json
import threading
import urllib.request

import numpy as np
import pytest

K = 5


def _post_knn(url, q, timeout=120):
    req = urllib.request.Request(
        url + "/knn",
        data=json.dumps({"queries": np.asarray(q).tolist(),
                         "neighbors": True}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _routed_points():
    """600 rows: [0:295) cluster A, [295:300) copies of rows [595:600)
    (B-region outliers inside host 0's slab), [300:600) cluster B."""
    from tests.oracle import random_points

    a = random_points(295, seed=41, scale=0.4)
    b = (random_points(300, seed=42, scale=0.4) + np.float32(0.6))
    return np.concatenate([a, b[-5:], b]).astype(np.float32)


@pytest.fixture(scope="module")
def routed_pod():
    """Two in-process routed slab hosts (no global mesh — that is the
    point) + their URLs + the full point set."""
    from mpi_cuda_largescaleknn_tpu.models.sharding import slab_bounds
    from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
    from mpi_cuda_largescaleknn_tpu.serve.engine import ResidentKnnEngine
    from mpi_cuda_largescaleknn_tpu.serve.frontend import HostSliceServer

    points = _routed_points()
    servers = []
    for b, e in slab_bounds(len(points), 2):
        eng = ResidentKnnEngine(points[b:e], K, mesh=get_mesh(2),
                                engine="tiled", bucket_size=64,
                                max_batch=32, min_batch=16,
                                id_offset=b, emit="candidates")
        eng.warmup()
        srv = HostSliceServer(("127.0.0.1", 0), eng, routing="bounds")
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        srv.ready = True
        servers.append(srv)
    urls = [f"http://127.0.0.1:{s.server_address[1]}" for s in servers]
    yield urls, points
    for s in servers:
        s.close()


@pytest.fixture(scope="module")
def reference_engine():
    """One engine over the union of the slabs — PR-5's byte-identical
    stand-in for the replicate-everything pod."""
    from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
    from mpi_cuda_largescaleknn_tpu.serve.engine import ResidentKnnEngine

    eng = ResidentKnnEngine(_routed_points(), K, mesh=get_mesh(2),
                            engine="tiled", bucket_size=64,
                            max_batch=32, min_batch=16, merge="device")
    eng.warmup()
    return eng


@pytest.fixture(scope="module")
def frontend(routed_pod):
    from mpi_cuda_largescaleknn_tpu.serve.frontend import build_frontend

    urls, _ = routed_pod
    srv = build_frontend(urls, port=0, pipeline_depth=2)  # routing=auto
    srv.ready = True
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield srv
    srv.close()


class TestBoundsTable:
    def _table(self):
        from mpi_cuda_largescaleknn_tpu.serve.frontend import PodBoundsTable

        return PodBoundsTable([
            {"row_offset": 0, "n_points": 10, "shards": [
                {"lo": [0.0, 0.0, 0.0], "hi": [1.0, 1.0, 1.0], "count": 8},
                {"lo": None, "hi": None, "count": 0},  # empty-shard sentinel
                {"lo": [2.0, 2.0, 2.0], "hi": [3.0, 3.0, 3.0], "count": 2},
            ]},
            {"row_offset": 10, "n_points": 0, "shards": [
                {"lo": None, "hi": None, "count": 0},
            ]},
        ], dim=3)

    def test_lower_bounds_math(self):
        t = self._table()
        q = np.array([[0.5, 0.5, 0.5],    # inside shard 0's box
                      [1.5, 0.5, 0.5],    # 0.5 off one face
                      [4.0, 4.0, 4.0]],   # nearest is shard 2's corner
                     np.float32)
        lb = t.lower_bounds(q)
        assert lb.shape == (3, 2)
        np.testing.assert_allclose(lb[:, 0], [0.0, 0.25, 3.0], atol=1e-12)
        # host 1 has no points anywhere: unreachable, never routed
        assert np.all(np.isinf(lb[:, 1]))

    def test_empty_host_never_nearest(self):
        t = self._table()
        lb = t.lower_bounds(np.zeros((4, 3), np.float32))
        assert np.all(np.argmin(lb, axis=1) == 0)

    def test_malformed_nonempty_shard_without_box_raises(self):
        from mpi_cuda_largescaleknn_tpu.serve.frontend import PodBoundsTable

        with pytest.raises(ValueError, match="malformed"):
            PodBoundsTable([{"row_offset": 0, "n_points": 3, "shards": [
                {"lo": None, "hi": None, "count": 3}]}], dim=3)

    def test_aabb_lower_bound_is_a_true_bound(self):
        from mpi_cuda_largescaleknn_tpu.utils.math import (
            aabb_lower_bound_dist2,
        )
        from tests.oracle import pairwise_dist2_np, random_points

        pts = random_points(64, seed=7)
        q = random_points(16, seed=8, scale=2.0)
        lo = pts.min(axis=0, keepdims=True)
        hi = pts.max(axis=0, keepdims=True)
        lb = aabb_lower_bound_dist2(q, lo, hi)[:, 0]
        d2 = pairwise_dist2_np(q, pts).min(axis=1)
        assert np.all(lb <= d2 + 1e-12)


class TestRoutedServedBitIdentical:
    def test_ragged_batches_match_reference(self, frontend, routed_pod,
                                            reference_engine):
        """Mixed A/B/duplicate queries at every shape bucket: distances
        AND tie ids byte-equal to the one-engine reference, and true
        k-NN against the numpy oracle."""
        _urls, points = routed_pod
        base = f"http://127.0.0.1:{frontend.server_address[1]}"
        from tests.oracle import kth_nn_dist, random_points

        for n in (1, 5, 16, 17, 32):
            q = random_points(n, seed=300 + n)  # spans A, B, and the gap
            q[: n // 3] = points[295: 295 + n // 3]  # ON cross-host dups
            resp = _post_knn(base, q)
            want_d, want_n = reference_engine.query(q)
            got_d = np.asarray(resp["dists"], np.float32)
            got_n = np.asarray(resp["neighbors"], np.int32)
            np.testing.assert_array_equal(got_d, want_d)
            np.testing.assert_array_equal(got_n, want_n)
            np.testing.assert_allclose(got_d, kth_nn_dist(q, points, K),
                                       rtol=5e-7, atol=1e-37)

    def test_clustered_and_uniform_workloads_match(self, frontend,
                                                   routed_pod,
                                                   reference_engine):
        rng = np.random.default_rng(77)
        base = f"http://127.0.0.1:{frontend.server_address[1]}"
        batches = [
            (rng.random((24, 3)) * 0.4).astype(np.float32),          # A blob
            (rng.random((24, 3)) * 0.4 + 0.6).astype(np.float32),    # B blob
            rng.random((32, 3)).astype(np.float32),                  # uniform
        ]
        for q in batches:
            resp = _post_knn(base, q)
            want_d, want_n = reference_engine.query(q)
            np.testing.assert_array_equal(
                np.asarray(resp["dists"], np.float32), want_d)
            np.testing.assert_array_equal(
                np.asarray(resp["neighbors"], np.int32), want_n)

    def test_escalation_wave_forced_and_certification(self, frontend,
                                                      routed_pod,
                                                      reference_engine):
        """Gap queries sit INSIDE host 0's outlier-widened box (lb 0 —
        wave 1 goes there) but OUTSIDE host 1's; host 1's small positive
        bound still beats their wave-1 k-th distance, so they MUST
        escalate for correctness. A-region queries must certify after one
        host. Both visible in the fan-out's routing accounting, results
        exact throughout."""
        _urls, points = routed_pod
        base = f"http://127.0.0.1:{frontend.server_address[1]}"
        fan = frontend.fanout
        esc_before = fan.escalations

        rng = np.random.default_rng(88)      # gap queries in [0.5, 0.58]^3
        qb = (0.5 + 0.08 * rng.random((24, 3))).astype(np.float32)
        resp = _post_knn(base, qb)
        want_d, want_n = reference_engine.query(qb)
        np.testing.assert_array_equal(
            np.asarray(resp["dists"], np.float32), want_d)
        np.testing.assert_array_equal(
            np.asarray(resp["neighbors"], np.int32), want_n)
        assert fan.escalations > esc_before  # the second wave really ran

        qa = points[10:34].copy()            # deep-A queries
        resp = _post_knn(base, qa)
        want_d, want_n = reference_engine.query(qa)
        np.testing.assert_array_equal(
            np.asarray(resp["dists"], np.float32), want_d)
        np.testing.assert_array_equal(
            np.asarray(resp["neighbors"], np.int32), want_n)
        hpq = fan.stats()["routing"]["hosts_per_query"]
        assert "1" in hpq and "2" in hpq  # some certified at one host

    def test_concurrent_clients_through_pipelined_fanout(self, frontend,
                                                         reference_engine):
        from tests.oracle import random_points

        base = f"http://127.0.0.1:{frontend.server_address[1]}"
        results = {}

        def client(i):
            q = random_points(3 + 2 * i, seed=900 + i)
            results[i] = (q, _post_knn(base, q))

        ths = [threading.Thread(target=client, args=(i,)) for i in range(6)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        assert len(results) == 6
        for q, resp in results.values():
            want_d, want_n = reference_engine.query(q)
            np.testing.assert_array_equal(
                np.asarray(resp["dists"], np.float32), want_d)
            np.testing.assert_array_equal(
                np.asarray(resp["neighbors"], np.int32), want_n)

    def test_mode_detection_and_mismatch(self, routed_pod):
        from mpi_cuda_largescaleknn_tpu.serve.frontend import (
            pod_config_from_hosts,
        )

        urls, points = routed_pod
        cfg = pod_config_from_hosts(urls)  # auto
        assert cfg["routing"] == "bounds"
        assert cfg["n_points"] == len(points)
        assert [h["row_offset"] for h in cfg["bounds_hosts"]] == [0, 300]
        with pytest.raises(ValueError, match="routing='off'"):
            pod_config_from_hosts(urls, routing="off")
        # a hole in the slab tiling is a hard error, not a warning
        with pytest.raises(ValueError, match="tile the index"):
            pod_config_from_hosts([urls[1]], routing="bounds")

    def test_observability_surface(self, frontend, routed_pod):
        """Per-shard AABBs + routed-row counters on the hosts; escalation
        counter, per-host routed rows, and the hosts-per-query histogram
        on the front end; loadgen's /stats projection carries the routed
        share + escalation rate."""
        urls, _ = routed_pod
        base = f"http://127.0.0.1:{frontend.server_address[1]}"
        for url in urls:
            with urllib.request.urlopen(url + "/stats", timeout=30) as r:
                st = json.loads(r.read())
            assert st["routing"] == "bounds"
            sb = st["engine"]["shard_bounds"]
            assert sum(s["count"] for s in sb) == st["engine"]["n_points"]
            assert all(s["lo"] is not None for s in sb if s["count"])
            assert st["server"].get("knn_routed_rows_total", 0) > 0
            m = urllib.request.urlopen(url + "/metrics",
                                       timeout=30).read().decode()
            assert "knn_routed_rows_total" in m
            assert "knn_host_routed 1" in m

        with urllib.request.urlopen(base + "/stats", timeout=30) as r:
            stats = json.loads(r.read())
        routing = stats["fanout"]["routing"]
        assert routing["mode"] == "bounds"
        assert routing["escalations"] > 0
        assert set(routing["routed_rows"]) == set(urls)
        assert sum(routing["routed_rows"].values()) > 0
        assert routing["hosts_per_query_mean"] is not None
        assert "complete_seconds_total" in stats["batcher"]

        m = urllib.request.urlopen(base + "/metrics",
                                   timeout=30).read().decode()
        assert "knn_routing_escalations_total" in m
        for url in urls:
            assert f'knn_routed_rows_total{{host="{url}"}}' in m
        assert 'knn_hosts_per_query_bucket{le="+Inf"}' in m

        from tools.loadgen import _server_pipeline_stats

        proj = _server_pipeline_stats(base, 30.0)
        assert proj["routing_mode"] == "bounds"
        assert proj["routing_escalations"] > 0
        assert abs(sum(proj["routed_row_share"].values()) - 1.0) < 1e-6
        assert proj["hosts_per_query_mean"] >= 1.0


class TestRoutedSingleHost:
    def test_h1_pod_matches_reference(self, reference_engine):
        """H=1 routed pod: one slab host owning everything — routing is
        the identity, results still byte-equal."""
        from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
        from mpi_cuda_largescaleknn_tpu.serve.engine import ResidentKnnEngine
        from mpi_cuda_largescaleknn_tpu.serve.frontend import (
            HostSliceServer,
            build_frontend,
        )
        from tests.oracle import random_points

        points = _routed_points()
        eng = ResidentKnnEngine(points, K, mesh=get_mesh(2),
                                engine="tiled", bucket_size=64,
                                max_batch=32, min_batch=16,
                                emit="candidates")
        host = HostSliceServer(("127.0.0.1", 0), eng, routing="bounds")
        threading.Thread(target=host.serve_forever, daemon=True).start()
        host.ready = True
        fe = None
        try:
            url = f"http://127.0.0.1:{host.server_address[1]}"
            fe = build_frontend([url], port=0, pipeline_depth=2)
            fe.ready = True
            threading.Thread(target=fe.serve_forever, daemon=True).start()
            base = f"http://127.0.0.1:{fe.server_address[1]}"
            q = random_points(17, seed=5)
            q[:4] = points[295:299]
            resp = _post_knn(base, q)
            want_d, want_n = reference_engine.query(q)
            np.testing.assert_array_equal(
                np.asarray(resp["dists"], np.float32), want_d)
            np.testing.assert_array_equal(
                np.asarray(resp["neighbors"], np.int32), want_n)
        finally:
            if fe is not None:
                fe.close()
            host.close()


class TestRadiusAndFoldDiscipline:
    """The fold itself (no HTTP): radius-capped + under-full rows keep the
    engines' strict-< adoption through the cross-host merge."""

    def _slab_engines(self, points, max_radius=np.inf):
        from mpi_cuda_largescaleknn_tpu.models.sharding import slab_bounds
        from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
        from mpi_cuda_largescaleknn_tpu.serve.engine import ResidentKnnEngine

        return [ResidentKnnEngine(points[b:e], K, mesh=get_mesh(1),
                                  engine="tiled", bucket_size=32,
                                  max_batch=8, min_batch=8,
                                  max_radius=max_radius,
                                  id_offset=b, emit="candidates")
                for b, e in slab_bounds(len(points), 2)]

    def _fold_all(self, engines, q):
        from mpi_cuda_largescaleknn_tpu.serve.frontend import (
            _fold_candidates,
        )

        cur_d2 = np.full((len(q), K), np.inf, np.float32)
        cur_idx = np.full((len(q), K), -1, np.int32)
        rows = np.arange(len(q))
        for eng in engines:
            d2, idx = eng.complete_candidates(eng.dispatch(q))
            _fold_candidates(cur_d2, cur_idx, rows, d2, idx, K)
        return np.sqrt(cur_d2[:, K - 1]), cur_idx

    def test_radius_capped_and_underfull_rows(self):
        from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
        from mpi_cuda_largescaleknn_tpu.serve.engine import ResidentKnnEngine
        from tests.oracle import random_points

        points = random_points(40, seed=11)
        r = 0.15  # caps most rows under k=5 candidates
        ref = ResidentKnnEngine(points, K, mesh=get_mesh(1),
                                engine="tiled", bucket_size=32,
                                max_batch=8, min_batch=8, max_radius=r)
        q = random_points(8, seed=12)
        want_d, want_n = ref.query(q)
        got_d, got_n = self._fold_all(self._slab_engines(points, r), q)
        assert np.any(want_n == -1)  # the cap really bit
        np.testing.assert_array_equal(got_d, want_d)
        np.testing.assert_array_equal(got_n, want_n)

    def test_fold_is_wave_order_independent(self):
        from tests.oracle import random_points

        points = random_points(40, seed=13)
        engines = self._slab_engines(points)
        q = random_points(8, seed=14)
        q[:2] = points[35:37]  # ids on host 1, ties vs nothing on host 0
        d_fwd, n_fwd = self._fold_all(engines, q)
        d_rev, n_rev = self._fold_all(engines[::-1], q)
        np.testing.assert_array_equal(d_fwd, d_rev)
        np.testing.assert_array_equal(n_fwd, n_rev)

    def test_host_merge_candidate_rows_match_device_merge(self):
        """A routed host may run either merge placement locally; the full
        candidate rows it serves must be identical — the host-merge path
        rides the full-width variant of the PR-3 numpy fold."""
        from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
        from mpi_cuda_largescaleknn_tpu.serve.engine import ResidentKnnEngine
        from tests.oracle import random_points

        points = random_points(64, seed=15)
        twins = [ResidentKnnEngine(points, K, mesh=get_mesh(2),
                                   engine="tiled", bucket_size=32,
                                   max_batch=8, min_batch=8, merge=m,
                                   emit="candidates")
                 for m in ("host", "device")]
        q = random_points(8, seed=16)
        q[:3] = points[10:13]  # exact hits -> boundary ties
        outs = [e.complete_candidates(e.dispatch(q)) for e in twins]
        np.testing.assert_array_equal(outs[0][0], outs[1][0])
        np.testing.assert_array_equal(outs[0][1], outs[1][1])
        # ascending canonical rows, -1 only in under-full slots
        assert np.all(np.diff(outs[0][0], axis=1) >= 0)
