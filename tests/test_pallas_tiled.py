"""Bucketed-traversal Pallas kernel (ops/pallas/knn_tiled.py) vs oracle and
vs its XLA twin — interpreter mode on the CPU fixture."""

import jax.numpy as jnp
import numpy as np
import pytest

from mpi_cuda_largescaleknn_tpu.ops.candidates import (
    extract_final_result,
    init_candidates,
)
from mpi_cuda_largescaleknn_tpu.ops.pallas.knn_tiled import (
    knn_update_tiled_pallas,
)
from mpi_cuda_largescaleknn_tpu.ops.partition import (
    partition_points,
    scatter_back,
)
from mpi_cuda_largescaleknn_tpu.ops.tiled import knn_update_tiled
from tests.oracle import assert_dist_equal, kth_nn_dist, random_points


def pallas_self_knn(pts, k, max_radius=np.inf, bucket_size=32):
    q = partition_points(jnp.asarray(pts), bucket_size=bucket_size)
    state = init_candidates(q.num_buckets * q.bucket_size, k, max_radius)
    state = knn_update_tiled_pallas(state, q, q)
    d = extract_final_result(state).reshape(q.num_buckets, q.bucket_size)
    return np.asarray(scatter_back(d, q.pos, len(pts), fill=jnp.inf))


@pytest.mark.parametrize("n,k", [(100, 1), (257, 8), (600, 13)])
def test_matches_oracle(n, k):
    pts = random_points(n, seed=n)
    assert_dist_equal(pallas_self_knn(pts, k), kth_nn_dist(pts, pts, k))


def test_k_exceeds_n_gives_inf():
    pts = random_points(10, seed=1)
    assert np.all(np.isinf(pallas_self_knn(pts, 32)))


def test_max_radius_cutoff():
    pts = random_points(300, seed=9, scale=4.0)
    r = 0.35
    want = kth_nn_dist(pts, pts, 6, max_radius=r)
    assert_dist_equal(pallas_self_knn(pts, 6, max_radius=r), want)


def test_clustered_data_pruning_is_safe():
    rng = np.random.default_rng(11)
    a = (rng.random((150, 3)) * 0.1).astype(np.float32)
    b = (rng.random((150, 3)) * 0.1 + 50.0).astype(np.float32)
    pts = np.concatenate([a, b]).astype(np.float32)
    want = kth_nn_dist(pts, pts, 5)
    assert_dist_equal(pallas_self_knn(pts, 5, bucket_size=16), want)


def test_matches_xla_twin_exactly():
    pts = random_points(500, seed=21)
    k = 7
    q = partition_points(jnp.asarray(pts), bucket_size=16)
    init = init_candidates(q.num_buckets * q.bucket_size, k)
    xla = knn_update_tiled(init, q, q)
    pal = knn_update_tiled_pallas(init, q, q)
    np.testing.assert_allclose(np.asarray(xla.dist2), np.asarray(pal.dist2),
                               rtol=1e-6)


def test_adoption_across_shards():
    pts = random_points(300, seed=17)
    a, b = pts[:151], pts[151:]
    k = 9
    q = partition_points(jnp.asarray(pts), bucket_size=16)
    pa = partition_points(jnp.asarray(a), jnp.arange(151, dtype=jnp.int32),
                          bucket_size=16)
    pb = partition_points(jnp.asarray(b), jnp.arange(151, 300, dtype=jnp.int32),
                          bucket_size=16)
    state = init_candidates(q.num_buckets * q.bucket_size, k)
    state = knn_update_tiled_pallas(state, q, pa)
    state = knn_update_tiled_pallas(state, q, pb)
    d = extract_final_result(state).reshape(q.num_buckets, q.bucket_size)
    got = np.asarray(scatter_back(d, q.pos, len(pts), fill=jnp.inf))
    assert_dist_equal(got, kth_nn_dist(pts, pts, k))


@pytest.mark.parametrize("visit_batch", [1, 2, 3])
def test_partial_final_chunk_masks_duplicates(visit_batch):
    # 5 buckets with V=3 pads the final chunk by duplicating bucket 4: the
    # duplicate lanes must be masked, or every point of bucket 4 would be
    # folded twice and displace true candidates
    pts = random_points(5 * 16, seed=41)
    k = 6
    q = partition_points(jnp.asarray(pts), bucket_size=16)
    assert q.num_buckets == 8  # pow2 bucket count; 3 buckets are all-pad
    state = init_candidates(q.num_buckets * q.bucket_size, k)
    state = knn_update_tiled_pallas(state, q, q, visit_batch=visit_batch)
    d = extract_final_result(state).reshape(q.num_buckets, q.bucket_size)
    got = np.asarray(scatter_back(d, q.pos, len(pts), fill=jnp.inf))
    assert_dist_equal(got, kth_nn_dist(pts, pts, k))


def test_k100_matches_oracle():
    pts = random_points(500, seed=43)
    assert_dist_equal(pallas_self_knn(pts, 100, bucket_size=64),
                      kth_nn_dist(pts, pts, 100))


def test_ring_pallas_tiled_8dev_matches_oracle():
    import jax

    from mpi_cuda_largescaleknn_tpu.core.config import KnnConfig
    from mpi_cuda_largescaleknn_tpu.models.unordered import UnorderedKNN
    from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh

    pts = random_points(610, seed=23)
    k = 6
    cfg = KnnConfig(k=k, engine="pallas_tiled", bucket_size=16)
    got = UnorderedKNN(cfg, mesh=get_mesh(len(jax.devices()))).run(pts)
    assert_dist_equal(got, kth_nn_dist(pts, pts, k))


def test_demand_pallas_tiled_matches_oracle():
    from mpi_cuda_largescaleknn_tpu.core.config import KnnConfig
    from mpi_cuda_largescaleknn_tpu.models.prepartitioned import (
        PrePartitionedKNN,
    )
    from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh

    pts = random_points(640, seed=31)
    pts = pts[np.argsort(pts[:, 0], kind="stable")]
    parts = [pts[i * 80:(i + 1) * 80] for i in range(8)]
    cfg = KnnConfig(k=5, engine="pallas_tiled", bucket_size=16)
    model = PrePartitionedKNN(cfg, mesh=get_mesh(8))
    got = np.concatenate(model.run(parts))
    assert_dist_equal(got, kth_nn_dist(pts, pts, 5))


def test_fold_segments_bitidentical():
    """Multi-extract fold (segments>1) must produce byte-identical candidate
    rows to the global extract-min (segments=1), including boundary ties
    from duplicated points."""
    import jax.numpy as jnp

    from mpi_cuda_largescaleknn_tpu.ops.pallas.knn_bf import (
        fold_tile_into_candidates,
    )

    rng = np.random.default_rng(8)
    s, t, k = 16, 512, 100
    d2 = rng.random((s, t)).astype(np.float32)
    d2[:, 128:256] = d2[:, :128]          # exact value ties across segments
    cd2 = np.full((s, k), np.inf, np.float32)
    cidx = np.full((s, k), -1, np.int32)
    base_d2, base_idx, base_p = fold_tile_into_candidates(
        jnp.asarray(d2), 0, jnp.asarray(cd2),
        jnp.asarray(cidx), with_passes=True, segments=1)
    for nseg in (2, 4, 16):
        g_d2, g_idx, g_p = fold_tile_into_candidates(
            jnp.asarray(d2), 0, jnp.asarray(cd2),
            jnp.asarray(cidx), with_passes=True, segments=nseg)
        np.testing.assert_array_equal(np.asarray(g_d2), np.asarray(base_d2))
        np.testing.assert_array_equal(np.asarray(g_idx), np.asarray(base_idx))
        assert int(g_p) < int(base_p), (nseg, int(g_p), int(base_p))

    # uneven granule count (17 x 128 lanes, 16 segments: the leading
    # segment absorbs the remainder granule) stays bit-identical
    t2 = 2176
    d2b = rng.random((s, t2)).astype(np.float32)
    ref_d2, ref_idx = fold_tile_into_candidates(
        jnp.asarray(d2b), 0, jnp.asarray(cd2),
        jnp.asarray(cidx), segments=1)
    got_d2, got_idx = fold_tile_into_candidates(
        jnp.asarray(d2b), 0, jnp.asarray(cd2),
        jnp.asarray(cidx), segments=16)
    np.testing.assert_array_equal(np.asarray(got_d2), np.asarray(ref_d2))
    np.testing.assert_array_equal(np.asarray(got_idx), np.asarray(ref_idx))


def test_neighbor_ids_decode_exactly():
    """The kernel stores encoded lane positions and the wrapper decodes
    them to point ids (fold_tile_into_candidates); every stored (d2, id)
    pair must recompute exactly, ids must be unique per row, and the
    decode must survive the mixed case where warm-started rows already
    hold real ids (the fused driver's path: warm_start_self + skip_self +
    a coarsened point side)."""
    from mpi_cuda_largescaleknn_tpu.ops.partition import coarsen_buckets
    from mpi_cuda_largescaleknn_tpu.ops.tiled import warm_start_self

    pts = random_points(600, seed=33)
    k = 7
    q = partition_points(jnp.asarray(pts), bucket_size=16)
    pc = coarsen_buckets(q, 4)
    warm = warm_start_self(pc, k)
    state = knn_update_tiled_pallas(warm, q, pc, skip_self=jnp.int32(1),
                                    self_group=4)
    d2 = np.asarray(state.dist2)
    idx = np.asarray(state.idx)
    qpts = np.asarray(q.pts).reshape(-1, 3)
    qids = np.asarray(q.ids).reshape(-1)
    for row in np.nonzero(qids >= 0)[0]:
        finite = np.isfinite(d2[row])
        ids_row = idx[row][finite]
        assert np.all(ids_row >= 0), (row, idx[row])
        assert len(np.unique(ids_row)) == len(ids_row), (row, ids_row)
        recomputed = ((qpts[row] - pts[ids_row]) ** 2).sum(axis=1)
        # tight tolerance, not bit-equality: the kernel's FMA-contracted
        # f32 sum can differ from numpy by 1 ulp; a WRONG id would be off
        # by orders of magnitude on random points
        np.testing.assert_allclose(recomputed.astype(np.float32),
                                   d2[row][finite], rtol=1e-5, atol=1e-9)
