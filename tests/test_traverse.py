import numpy as np
import pytest

from mpi_cuda_largescaleknn_tpu.core.types import pad_points
from mpi_cuda_largescaleknn_tpu.ops.build_tree import build_tree
from mpi_cuda_largescaleknn_tpu.ops.candidates import extract_final_result, init_candidates
from mpi_cuda_largescaleknn_tpu.ops.traverse import knn_update_tree

from .oracle import assert_dist_equal, kth_nn_dist, random_points




@pytest.mark.parametrize("n,k", [(50, 1), (100, 5), (257, 8), (600, 20)])
def test_traversal_matches_oracle(n, k):
    pts = random_points(n, seed=n)
    tree, tree_ids = build_tree(pts)
    st = init_candidates(n, k)
    st = knn_update_tree(st, pts, tree, tree_ids)
    got = np.array(extract_final_result(st))
    want = kth_nn_dist(pts, pts, k)
    assert_dist_equal(got, want)


def test_traversal_with_radius():
    pts = random_points(300, seed=11)
    k, r = 6, 0.08
    tree, tree_ids = build_tree(pts)
    st = init_candidates(300, k, max_radius=r)
    st = knn_update_tree(st, pts, tree, tree_ids)
    assert_dist_equal(np.array(extract_final_result(st)),
                      kth_nn_dist(pts, pts, k, max_radius=r))


def test_traversal_k_exceeds_n():
    pts = random_points(6, seed=3)
    tree, tree_ids = build_tree(pts)
    st = knn_update_tree(init_candidates(6, 9), pts, tree, tree_ids)
    assert np.all(np.isinf(np.array(extract_final_result(st))))


def test_traversal_on_sentinel_padded_tree():
    pts = random_points(100, seed=13)
    padded, _ = pad_points(pts, 128)
    tree, tree_ids = build_tree(padded)
    st = knn_update_tree(init_candidates(100, 4), pts, tree, tree_ids)
    assert_dist_equal(np.array(extract_final_result(st)),
                      kth_nn_dist(pts, pts, 4))


def test_empty_tree_is_noop():
    pts = random_points(10, seed=1)
    st0 = init_candidates(10, 3)
    st = knn_update_tree(st0, pts, np.zeros((0, 3), np.float32),
                         np.zeros((0,), np.int32))
    np.testing.assert_array_equal(np.array(st.dist2), np.array(st0.dist2))
