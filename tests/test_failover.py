"""Fault-tolerant serving: drain/rejoin, retry/backoff, degraded answers.

Everything here is DETERMINISTIC — no sleep-based races. Failures are
produced by serve/faults.py injectors (seeded, countable), time is an
injectable clock in every unit test, retry backoff sleeps are captured by
a recorder instead of slept, and the health monitor is driven by explicit
``check_once()`` calls rather than its background thread.

The integration fixture is a 2-host routed pod with spatially DISJOINT
slabs (cluster A rows 0..299 on host 0, cluster B rows 300..599 on
host 1), so "the certified routing set touches the drained slab" is an
exact, predictable property: A-region queries certify at host 0 and must
stay BIT-IDENTICAL to a never-failed pod while host 1 is down; B-region
queries are exactly the degraded/refused set.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

K = 5


def _post_knn(url, q, timeout=120):
    req = urllib.request.Request(
        url + "/knn",
        data=json.dumps({"queries": np.asarray(q).tolist(),
                         "neighbors": True}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _post_faults(url, spec: str, timeout=30):
    req = urllib.request.Request(
        url + "/faults", data=json.dumps({"spec": spec}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _failover_points():
    """600 rows: [0:300) cluster A in [0, 0.4)^3, [300:600) cluster B in
    [0.6, 1.0)^3 — disjoint slabs, so routing decisions are clean."""
    from tests.oracle import random_points

    a = random_points(300, seed=51, scale=0.4)
    b = random_points(300, seed=52, scale=0.4) + np.float32(0.6)
    return np.concatenate([a, b]).astype(np.float32)


# ---------------------------------------------------------------- unit layer


class TestBackoff:
    def test_capped_exponential_and_deterministic(self):
        from mpi_cuda_largescaleknn_tpu.serve.health import Backoff

        b = Backoff(base_s=0.1, cap_s=1.0, factor=2.0, jitter=0.0, seed=0)
        delays = [b.delay(i) for i in range(1, 7)]
        assert delays[:4] == [0.1, 0.2, 0.4, 0.8]
        assert delays[4] == delays[5] == 1.0  # capped
        # same seed+key -> identical sequence; different key -> different
        # jitter, still within [d, d * (1 + jitter)]
        j1 = Backoff(base_s=0.1, cap_s=1.0, jitter=0.5, seed=7)
        j2 = Backoff(base_s=0.1, cap_s=1.0, jitter=0.5, seed=7)
        s1 = [j1.delay(i, key="hostA") for i in range(1, 5)]
        assert s1 == [j2.delay(i, key="hostA") for i in range(1, 5)]
        assert s1 != [j1.delay(i, key="hostB") for i in range(1, 5)]
        for i, d in enumerate(s1, start=1):
            base = min(1.0, 0.1 * 2.0 ** (i - 1))
            assert base <= d <= base * 1.5


class TestFaultInjector:
    def test_parse_and_counting(self):
        from mpi_cuda_largescaleknn_tpu.serve.faults import (
            FaultInjector,
            parse_fault_specs,
        )

        specs = parse_fault_specs(
            "error:path=/route_knn,n=2,code=503;latency:delay_s=0.01")
        assert [s.op for s in specs] == ["error", "latency"]
        assert specs[0].code == 503
        inj = FaultInjector(specs)
        # first two /route_knn requests hit the error budget, later ones
        # fall through to the catch-all latency rule
        ops = [inj.decide("/route_knn").op for _ in range(4)]
        assert ops == ["error", "error", "latency", "latency"]
        # a path the error rule doesn't match only sees latency
        assert inj.decide("/healthz").op == "latency"
        inj.clear()
        assert inj.decide("/route_knn") is None and not inj.active()

    def test_after_skips_then_arms(self):
        from mpi_cuda_largescaleknn_tpu.serve.faults import (
            FaultInjector,
            parse_fault_specs,
        )

        inj = FaultInjector(parse_fault_specs("drop:after=2,n=1"))
        assert [inj.decide("/x") for _ in range(4)][:2] == [None, None]
        assert inj.config()[0]["fires"] == 1

    def test_probabilistic_sequence_is_seed_deterministic(self):
        from mpi_cuda_largescaleknn_tpu.serve.faults import (
            FaultInjector,
            parse_fault_specs,
        )

        def seq(seed):
            inj = FaultInjector(parse_fault_specs(f"drop:p=0.5,seed={seed}"))
            return [inj.decide("/x") is not None for _ in range(32)]

        assert seq(9) == seq(9)       # reproducible
        assert seq(9) != seq(10)      # and actually seed-driven
        assert 4 < sum(seq(9)) < 28   # a real coin, not constant

    def test_method_filter_restricts_the_match(self):
        """``method=POST`` kills the serving path while GET probes keep
        answering — the probes-lie failure mode the fan-out's per-batch
        budget covers; the filter is part of the MATCH, so the skip/fire
        counters only see requests the rule could hit."""
        from mpi_cuda_largescaleknn_tpu.serve.faults import (
            FaultInjector,
            parse_fault_specs,
        )

        inj = FaultInjector(parse_fault_specs(
            "drop:path=/route_knn,method=POST"))
        assert inj.decide("/route_knn", "POST") is not None
        assert inj.decide("/route_knn", "GET") is None
        assert inj.decide("/healthz", "POST") is None
        # an unfiltered rule still matches any verb (back-compat)
        inj.set_specs("drop:")
        assert inj.decide("/x", "GET") is not None
        assert inj.decide("/x") is not None

    def test_unknown_op_and_key_raise(self):
        from mpi_cuda_largescaleknn_tpu.serve.faults import parse_fault_specs

        with pytest.raises(ValueError, match="unknown fault op"):
            parse_fault_specs("explode:")
        with pytest.raises(ValueError, match="unknown fault spec key"):
            parse_fault_specs("drop:bogus=1")


class TestHostHealth:
    def _health(self, clock, **kw):
        from mpi_cuda_largescaleknn_tpu.serve.health import HostHealth

        kw.setdefault("fail_threshold", 3)
        return HostHealth(clock=clock, **kw)

    def test_drains_at_threshold_and_success_resets(self):
        t = {"now": 0.0}
        h = self._health(lambda: t["now"])
        h.note_failure("e1")
        assert h.state == "suspect" and h.consecutive_failures == 1
        h.note_success()
        assert h.state == "healthy" and h.consecutive_failures == 0
        for i in range(3):
            h.note_failure(f"e{i}")
        assert h.state == "drained" and h.is_drained()

    def test_drained_seconds_accounting_with_fake_clock(self):
        t = {"now": 100.0}
        h = self._health(lambda: t["now"], fail_threshold=1)
        h.note_failure("down")
        t["now"] = 107.5
        assert h.drained_seconds() == pytest.approx(7.5)
        h.mark_rejoining()
        t["now"] = 110.0
        h.mark_rejoined()
        assert h.state == "healthy"
        assert h.drained_seconds() == pytest.approx(10.0)
        t["now"] = 200.0  # healthy time never accrues
        assert h.drained_seconds() == pytest.approx(10.0)

    def test_rejoin_failure_returns_to_drained(self):
        t = {"now": 0.0}
        h = self._health(lambda: t["now"], fail_threshold=1)
        h.note_failure("down")
        h.mark_rejoining()
        h.rejoin_failed("fingerprint mismatch")
        assert h.state == "drained"
        assert "fingerprint" in h.last_error

    def test_probe_scheduling_backoff_while_drained(self):
        t = {"now": 0.0}
        h = self._health(lambda: t["now"], fail_threshold=1,
                         probe_interval_s=5.0, backoff_base_s=1.0,
                         backoff_cap_s=4.0, jitter=0.0)
        assert h.probe_due(0.0)
        nxt = h.schedule_next_probe(now=0.0)
        assert nxt == 5.0 and not h.probe_due(4.9) and h.probe_due(5.0)
        h.note_failure("down")  # drained: capped exponential takes over
        delays = []
        now = 10.0
        for _ in range(4):
            nxt = h.schedule_next_probe(now=now)
            delays.append(nxt - now)
            now = nxt
        assert delays == [1.0, 2.0, 4.0, 4.0]

    def test_backoff_resets_after_rejoin_and_is_exposed(self):
        """A successful rejoin must reset the drained-probe backoff: a
        LATER flap restarts from the base interval, never the cap — and
        ``backoff_current_s`` surfaces the live value per host (the
        frontend /stats pod.health block carries the snapshot)."""
        t = {"now": 0.0}
        h = self._health(lambda: t["now"], fail_threshold=1,
                         probe_interval_s=5.0, backoff_base_s=1.0,
                         backoff_cap_s=8.0, jitter=0.0)
        assert h.snapshot()["backoff_current_s"] == 0.0  # healthy: none
        h.note_failure("down")
        now = 0.0
        for _ in range(3):  # ride the exponential to the cap
            now = h.schedule_next_probe(now=now)
        assert h.snapshot()["backoff_current_s"] == 8.0  # at the cap
        h.mark_rejoining()
        h.mark_rejoined()
        assert h.snapshot()["backoff_current_s"] == 0.0  # reset with state
        # a later flap restarts the schedule from BASE, not the cap
        h.note_failure("down again")
        assert h.snapshot()["backoff_current_s"] == 1.0
        nxt = h.schedule_next_probe(now=100.0)
        assert nxt - 100.0 == 1.0


class _FakeFanout:
    """Minimal fan-out stand-in for monitor unit tests."""

    def __init__(self, urls, clock, health_kw=None):
        from mpi_cuda_largescaleknn_tpu.serve.health import HostHealth

        class _Ep:
            def __init__(self, url):
                self.url = url
                self.health = HostHealth(clock=clock,
                                         **(health_kw or
                                            {"fail_threshold": 1}))

        self.endpoints = [_Ep(u) for u in urls]
        self.broken = None
        self.resets: list[int] = []

    def reset_stream(self, seq):
        self.broken = None
        self.resets.append(seq)


class TestHealthMonitorUnit:
    def _monitor(self, fanout, probes, stats, fingerprints, mode="bounds"):
        from mpi_cuda_largescaleknn_tpu.serve.health import HealthMonitor

        return HealthMonitor(
            fanout, fingerprints=fingerprints, mode=mode,
            probe_fn=lambda url: probes[url].pop(0),
            stats_fn=lambda url: stats[url], clock=lambda: 0.0)

    def test_probe_failures_drain_then_matching_fingerprint_rejoins(self):
        from mpi_cuda_largescaleknn_tpu.serve.health import host_fingerprint

        t = {"now": 0.0}
        fan = _FakeFanout(["u1"], lambda: t["now"],
                          {"fail_threshold": 2, "jitter": 0.0})
        engine = {"k": 5, "dim": 3, "row_offset": 0, "n_points": 10}
        fp = host_fingerprint(engine, "bounds")
        probes = {"u1": [(False, {"error": "boom"}),
                         (False, {"error": "boom"}),
                         (True, {}), (True, {})]}
        mon = self._monitor(fan, probes, {"u1": {"engine": engine}},
                            {"u1": fp})
        h = fan.endpoints[0].health
        mon.check_once(now=0.0)
        assert h.state == "suspect"
        mon.check_once(now=h.next_probe_at)
        assert h.state == "drained"
        mon.check_once(now=h.next_probe_at)
        assert h.state == "healthy" and mon.rejoins == 1

    def test_fingerprint_mismatch_blocks_rejoin(self):
        from mpi_cuda_largescaleknn_tpu.serve.health import host_fingerprint

        fan = _FakeFanout(["u1"], lambda: 0.0,
                          {"fail_threshold": 1, "jitter": 0.0})
        good = host_fingerprint({"k": 5, "row_offset": 0}, "bounds")
        # the restarted host came back serving a DIFFERENT slab
        probes = {"u1": [(False, {"error": "x"}), (True, {})]}
        stats = {"u1": {"engine": {"k": 5, "row_offset": 300}}}
        mon = self._monitor(fan, probes, stats, {"u1": good})
        h = fan.endpoints[0].health
        mon.check_once(now=0.0)
        assert h.state == "drained"
        mon.check_once(now=h.next_probe_at)
        assert h.state == "drained" and mon.rejoin_rejections == 1
        assert "row_offset" in h.last_error

    def test_replicate_pod_reset_needs_seq_consensus(self):
        from mpi_cuda_largescaleknn_tpu.serve.health import host_fingerprint

        fan = _FakeFanout(["u1", "u2"], lambda: 0.0,
                          {"fail_threshold": 1, "jitter": 0.0})
        fan.broken = "host u2 died"
        fan.endpoints[1].health.force_drain("died")
        engine = {"k": 5, "merge": "device"}
        fp = host_fingerprint(engine, "off")
        stats = {u: {"engine": engine} for u in ("u1", "u2")}
        # first pass: hosts disagree on next_seq -> no reset; second pass
        # (after the restart converges): consensus -> stream reset. The
        # reset path REUSES the cycle's probe results (no extra probes),
        # so each check_once consumes exactly one scripted result per host
        probes = {"u1": [(True, {"next_seq": 4}),
                         (True, {"next_seq": 0})],
                  "u2": [(True, {"next_seq": 0}),
                         (True, {"next_seq": 0})]}
        mon = self._monitor(fan, probes, stats, {"u1": fp, "u2": fp},
                            mode="off")
        mon.check_once(now=0.0)
        assert fan.broken is not None and fan.resets == []
        mon.check_once(now=1e9)  # everything due again
        assert fan.broken is None and fan.resets == [0]
        assert all(ep.health.state == "healthy" for ep in fan.endpoints)
        assert mon.stream_resets == 1


# --------------------------------------------------------- integration layer


@pytest.fixture(scope="module")
def routed_pod():
    """Two in-process routed slab hosts over disjoint clusters, with
    programmatic fault injectors."""
    from mpi_cuda_largescaleknn_tpu.models.sharding import slab_bounds
    from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
    from mpi_cuda_largescaleknn_tpu.serve.engine import ResidentKnnEngine
    from mpi_cuda_largescaleknn_tpu.serve.frontend import HostSliceServer

    points = _failover_points()
    servers = []
    for b, e in slab_bounds(len(points), 2):
        eng = ResidentKnnEngine(points[b:e], K, mesh=get_mesh(2),
                                engine="tiled", bucket_size=64,
                                max_batch=32, min_batch=16,
                                id_offset=b, emit="candidates")
        eng.warmup()
        srv = HostSliceServer(("127.0.0.1", 0), eng, routing="bounds")
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        srv.ready = True
        servers.append(srv)
    urls = [f"http://127.0.0.1:{s.server_address[1]}" for s in servers]
    yield urls, points, servers
    for s in servers:
        s.close()


@pytest.fixture(scope="module")
def reference_engine():
    """One engine over the union — the never-failed pod's byte-identical
    stand-in (PR 7's parity chain)."""
    from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
    from mpi_cuda_largescaleknn_tpu.serve.engine import ResidentKnnEngine

    eng = ResidentKnnEngine(_failover_points(), K, mesh=get_mesh(2),
                            engine="tiled", bucket_size=64,
                            max_batch=32, min_batch=16, merge="device")
    eng.warmup()
    return eng


@pytest.fixture()
def clean_faults(routed_pod):
    """Every test starts and ends with injection off on both hosts."""
    _, _, servers = routed_pod
    for s in servers:
        s.faults.clear()
    yield
    for s in servers:
        s.faults.clear()


def _build_fe(urls, **kw):
    from mpi_cuda_largescaleknn_tpu.serve.frontend import build_frontend

    kw.setdefault("on_host_loss", "degrade")
    kw.setdefault("retries", 1)
    kw.setdefault("retry_backoff_s", 0.001)
    kw.setdefault("fail_threshold", 2)
    kw.setdefault("start_monitor", False)
    # these tests re-post identical probes to drive the failure paths;
    # the exact-hit query cache would serve the repeat without ever
    # reaching the faulted host, so it stays off here
    kw.setdefault("qcache_rows", 0)
    srv = build_frontend(urls, port=0, pipeline_depth=2, **kw)
    srv.ready = True
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, f"http://127.0.0.1:{srv.server_address[1]}"


class TestRetryWithBackoff:
    def test_transient_5xx_is_retried_and_recorded(self, routed_pod,
                                                   reference_engine,
                                                   clean_faults):
        from tests.oracle import random_points

        urls, _points, servers = routed_pod
        fe, base = _build_fe(urls, retries=3)
        try:
            slept = []
            fe.fanout._sleep = slept.append  # retries never really sleep
            # host 0 fails its next 2 /route_knn posts, then recovers —
            # inside the retry budget, so the request must succeed exactly
            servers[0].faults.set_specs("error:path=/route_knn,n=2")
            q = random_points(8, seed=70, scale=0.4)  # A-region: host 0
            resp = _post_knn(base, q)
            want_d, want_n = reference_engine.query(q)
            np.testing.assert_array_equal(
                np.asarray(resp["dists"], np.float32), want_d)
            np.testing.assert_array_equal(
                np.asarray(resp["neighbors"], np.int32), want_n)
            assert resp["exact"] is True
            ep = fe.fanout.endpoints[0]
            assert ep.retries == 2
            assert ep.health.state == "healthy"  # success reset the streak
            # the recorded backoff delays are exactly the deterministic
            # schedule (no RNG state shared with anything else)
            want = [fe.fanout.retry_backoff.delay(i, key=ep.url)
                    for i in (1, 2)]
            assert slept == want
            m = urllib.request.urlopen(base + "/metrics",
                                       timeout=30).read().decode()
            assert f'knn_dispatch_retries_total{{host="{ep.url}"}} 2' in m
        finally:
            fe.close()

    def test_nonretryable_4xx_is_not_retried(self, routed_pod, clean_faults):
        from mpi_cuda_largescaleknn_tpu.serve.frontend import HostCallError

        urls, _points, servers = routed_pod
        fe, _base = _build_fe(urls, retries=3)
        try:
            fe.fanout._sleep = lambda s: None
            servers[0].faults.set_specs("error:path=/route_knn,code=404,n=8")
            ep = fe.fanout.endpoints[0]
            with pytest.raises(HostCallError) as ei:
                fe.fanout._post_route(ep, b"\x00" * 12, 1)
            assert not ei.value.transient
            assert ep.retries == 0  # a config error is never retried
        finally:
            fe.close()


class TestDegradedMode:
    def test_host_loss_degrades_only_affected_queries(self, routed_pod,
                                                      reference_engine,
                                                      clean_faults):
        from tests.oracle import random_points

        urls, points, servers = routed_pod
        fe, base = _build_fe(urls, on_host_loss="degrade")
        try:
            # host 1 (cluster B's slab) goes down hard: every /route_knn
            # and /healthz answer is dropped mid-connection
            servers[1].faults.set_specs("drop:")
            qb = random_points(8, seed=71, scale=0.4) + np.float32(0.6)
            resp_b = _post_knn(base, qb)
            # B queries touch the drained slab: flagged, not refused
            assert resp_b["exact"] is False
            assert resp_b["exact_per_query"] == [False] * len(qb)
            assert fe.fanout.endpoints[1].health.state == "drained"
            # degraded answers are the fold of the SURVIVING host only —
            # byte-stable across repeats, and equal to host 0's slab truth
            resp_b2 = _post_knn(base, qb)
            assert resp_b2["dists"] == resp_b["dists"]
            assert resp_b2["neighbors"] == resp_b["neighbors"]
            from tests.oracle import kth_nn_dist

            np.testing.assert_allclose(
                np.asarray(resp_b["dists"], np.float32),
                kth_nn_dist(qb, points[:300], K), rtol=5e-7, atol=1e-37)
            # A queries never routed to host 1: still bit-identical to the
            # never-failed pod
            qa = random_points(8, seed=72, scale=0.4)
            resp_a = _post_knn(base, qa)
            assert resp_a["exact"] is True
            want_d, want_n = reference_engine.query(qa)
            np.testing.assert_array_equal(
                np.asarray(resp_a["dists"], np.float32), want_d)
            np.testing.assert_array_equal(
                np.asarray(resp_a["neighbors"], np.int32), want_n)
            # observability: counters + state gauge + stats block
            st = json.loads(urllib.request.urlopen(
                base + "/stats", timeout=30).read())
            assert st["pod"]["on_host_loss"] == "degrade"
            assert st["pod"]["health"][urls[1]]["state"] == "drained"
            assert st["server"]["knn_degraded_responses_total"] >= 2
            assert st["fanout"]["routing"]["degraded_rows"] >= len(qb)
            m = urllib.request.urlopen(base + "/metrics",
                                       timeout=30).read().decode()
            assert f'knn_host_state{{host="{urls[1]}"}} 2' in m
            assert "knn_degraded_responses_total" in m
            assert f'knn_host_drained_seconds_total{{host="{urls[1]}"}}' in m
            hz = json.loads(urllib.request.urlopen(
                base + "/healthz", timeout=30).read())
            assert hz["status"] in ("ok", "degraded")
        finally:
            fe.close()

    def test_rejoin_restores_bitwise_parity(self, routed_pod,
                                            reference_engine, clean_faults):
        from tests.oracle import random_points

        urls, _points, servers = routed_pod
        fe, base = _build_fe(urls, on_host_loss="degrade")
        try:
            probe = random_points(24, seed=73)  # spans A, B, and the gap
            before = _post_knn(base, probe)
            servers[1].faults.set_specs("drop:")
            degraded = _post_knn(base, probe)
            assert degraded["exact"] is False
            assert fe.fanout.endpoints[1].health.state == "drained"
            # outage over: clear the faults and drive the monitor by hand
            servers[1].faults.clear()
            fe.monitor.check_once(now=1e9)  # every probe due
            assert fe.fanout.endpoints[1].health.state == "healthy"
            assert fe.monitor.rejoins == 1
            after = _post_knn(base, probe)
            assert after["exact"] is True
            # the acceptance bar: bitwise parity with a never-failed pod
            assert after["dists"] == before["dists"]
            assert after["neighbors"] == before["neighbors"]
            want_d, want_n = reference_engine.query(probe)
            np.testing.assert_array_equal(
                np.asarray(after["dists"], np.float32), want_d)
            np.testing.assert_array_equal(
                np.asarray(after["neighbors"], np.int32), want_n)
        finally:
            fe.close()

    def test_runtime_fault_endpoint_drives_outage(self, routed_pod,
                                                  clean_faults):
        """The chaos bench's control surface: POST /faults flips a live
        host into an outage and back, no process restarts involved."""
        urls, _points, servers = routed_pod
        cfg = _post_faults(urls[1], "error:path=/route_knn,code=500")
        assert cfg["specs"][0]["code"] == 500
        assert servers[1].faults.active()
        cfg = _post_faults(urls[1], "")
        assert cfg["specs"] == [] and not servers[1].faults.active()


class TestFailMode:
    def test_affected_queries_503_unaffected_serve(self, routed_pod,
                                                   reference_engine,
                                                   clean_faults):
        from tests.oracle import random_points

        urls, _points, servers = routed_pod
        fe, base = _build_fe(urls, on_host_loss="fail")
        try:
            servers[1].faults.set_specs("drop:")
            qb = random_points(6, seed=74, scale=0.4) + np.float32(0.6)
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post_knn(base, qb)
            assert ei.value.code == 503
            assert ei.value.headers.get("Retry-After") is not None
            body = json.loads(ei.value.read())
            assert "drained" in body["error"]
            # unaffected queries still serve, bit-identical
            qa = random_points(6, seed=75, scale=0.4)
            resp = _post_knn(base, qa)
            want_d, want_n = reference_engine.query(qa)
            np.testing.assert_array_equal(
                np.asarray(resp["dists"], np.float32), want_d)
            np.testing.assert_array_equal(
                np.asarray(resp["neighbors"], np.int32), want_n)
            st = json.loads(urllib.request.urlopen(
                base + "/stats", timeout=30).read())
            assert st["server"]["knn_unavailable_total"] >= 1
        finally:
            fe.close()


class TestReplicateDrainThenFail:
    @pytest.fixture(scope="class")
    def off_pod(self):
        """A 1-host replicate-mode pod, in-process (the seq-stream
        contract is per-host, so H=1 exercises it fully)."""
        from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
        from mpi_cuda_largescaleknn_tpu.serve.engine import ResidentKnnEngine
        from mpi_cuda_largescaleknn_tpu.serve.frontend import HostSliceServer

        points = _failover_points()
        eng = ResidentKnnEngine(points, K, mesh=get_mesh(2),
                                engine="tiled", bucket_size=64,
                                max_batch=32, min_batch=16, merge="device")
        eng.warmup()
        srv = HostSliceServer(("127.0.0.1", 0), eng, routing="off")
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        srv.ready = True
        yield f"http://127.0.0.1:{srv.server_address[1]}", srv, eng
        srv.close()

    def test_break_503_then_clean_stream_reset(self, off_pod,
                                               reference_engine):
        from tests.oracle import random_points

        url, host_srv, _eng = off_pod
        host_srv.faults.clear()
        fe, base = _build_fe([url], on_host_loss="fail")
        try:
            q = random_points(8, seed=76)
            before = _post_knn(base, q)
            # one injected host failure breaks the collective stream
            host_srv.faults.set_specs("error:path=/shard_knn,n=1")
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post_knn(base, q)
            assert ei.value.code == 503  # drain-then-fail, not a 500
            assert ei.value.headers.get("Retry-After") is not None
            assert fe.fanout.broken is not None
            assert fe.fanout.endpoints[0].health.state == "drained"
            # while broken, requests fail FAST with 503 (no fan-out)
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post_knn(base, q)
            assert ei.value.code == 503
            # the injector's budget (n=1) is exhausted = the pod restarted
            # healthy; the monitor validates fingerprint + seq consensus
            # and resets the stream
            fe.monitor.check_once(now=1e9)
            assert fe.fanout.broken is None
            assert fe.monitor.stream_resets == 1
            assert fe.fanout.endpoints[0].health.state == "healthy"
            after = _post_knn(base, q)
            assert after["dists"] == before["dists"]
            assert after["neighbors"] == before["neighbors"]
            want_d, want_n = reference_engine.query(q)
            np.testing.assert_array_equal(
                np.asarray(after["dists"], np.float32), want_d)
            np.testing.assert_array_equal(
                np.asarray(after["neighbors"], np.int32), want_n)
        finally:
            fe.close()

    def test_seq_timeout_maps_to_503_retry_after(self, off_pod):
        url, host_srv, _eng = off_pod
        host_srv.faults.clear()
        # skip ahead of the stream: seq 10**6 can never be next — the
        # knobbed-down timeout turns the wait into a fast 503
        old = host_srv.seq_timeout_s
        host_srv.seq_timeout_s = 0.05
        try:
            body = np.zeros((1, 3), np.float32).tobytes()
            req = urllib.request.Request(
                url + "/shard_knn?seq=1000000", data=body,
                headers={"Content-Type": "application/octet-stream"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=30)
            assert ei.value.code == 503
            assert ei.value.headers.get("Retry-After") is not None
            assert "stream" in json.loads(ei.value.read())["error"]
        finally:
            host_srv.seq_timeout_s = old

    def test_seq_timeout_constructor_validation(self):
        from mpi_cuda_largescaleknn_tpu.serve.frontend import HostSliceServer

        class _Eng:  # the knob validates before touching the engine
            pass

        with pytest.raises(ValueError, match="seq_timeout_s"):
            HostSliceServer(("127.0.0.1", 0), _Eng(), routing="off",
                            seq_timeout_s=0.0)


class TestProbeErrorsSurfaced:
    def test_probe_and_scrape_failures_land_in_stats(self):
        from mpi_cuda_largescaleknn_tpu.serve.frontend import PodFanout

        # an address nothing listens on: both probes must fail LOUDLY into
        # the per-host accounting instead of being swallowed
        fan = PodFanout(["http://127.0.0.1:9"], k=2, max_batch=8)
        try:
            health = fan.probe_health(timeout_s=0.2)
            assert health["http://127.0.0.1:9"]["ok"] is False
            stats = fan.scrape_host_stats(timeout_s=0.2)
            assert "error" in stats["http://127.0.0.1:9"]
            per = fan.stats()["per_host"]["http://127.0.0.1:9"]
            assert per["probe_errors"] == 1
            assert per["scrape_errors"] == 1
            assert "failed" in per["last_error"]
        finally:
            fan.close()


class TestLoadgenAvailability:
    def test_report_carries_status_breakdown_and_degraded_rate(
            self, routed_pod, clean_faults):
        import tools.loadgen as loadgen

        urls, _points, servers = routed_pod
        fe, base = _build_fe(urls, on_host_loss="degrade")
        try:
            servers[1].faults.set_specs("drop:")
            rep = loadgen.run_load(base, duration_s=1.0, concurrency=2,
                                   batch=4, timeout_s=30, seed=3)
            assert rep["requests"] > 0
            assert set(rep["status_counts"]) >= {"200"}
            assert rep["availability"] is not None
            assert 0.0 <= rep["availability"] <= 1.0
            # uniform [0,1)^3 queries all touch cluster B's half of the
            # box, so with host 1 down most answers are degraded 200s
            assert rep["degraded"] > 0 and rep["degraded_rate"] > 0
            assert rep["ok"] == rep["status_counts"].get("200", 0)
        finally:
            fe.close()
