"""Spatial pre-partitioner tool (io/partition_file.py + native C++ path)."""

import numpy as np
import pytest

from mpi_cuda_largescaleknn_tpu.io.partition_file import (
    partition_float3_file,
    partition_float3_file_np,
)
from tests.oracle import assert_dist_equal, kth_nn_dist, random_points


def _read_parts(prefix, n):
    return [np.fromfile(f"{prefix}_{r:06d}.float3", np.float32).reshape(-1, 3)
            for r in range(n)]


def test_partition_preserves_points_and_balances(tmp_path):
    pts = random_points(4000, seed=3)
    inp = tmp_path / "in.float3"
    pts.tofile(inp)
    counts = partition_float3_file(str(inp), 8, str(tmp_path / "p"))
    assert counts.sum() == 4000
    # near-equal split: morton bins are fine-grained at 4000 points
    assert counts.max() - counts.min() <= 0.2 * 4000 / 8 + 64
    parts = _read_parts(str(tmp_path / "p"), 8)
    # the union of parts is exactly the input point multiset
    got = np.concatenate(parts)
    assert sorted(map(tuple, got.tolist())) == sorted(map(tuple, pts.tolist()))
    # file list written in prepartitioned_main's format
    names = (tmp_path / "p.txt").read_text().splitlines()
    assert len(names) == 8 and names[0].endswith("_000000.float3")


def test_native_and_numpy_paths_identical(tmp_path):
    pts = random_points(3000, seed=5, scale=3.0)
    inp = tmp_path / "in.float3"
    pts.tofile(inp)
    try:
        from mpi_cuda_largescaleknn_tpu.io.native import native_partition
        native_partition(str(inp), 4, str(tmp_path / "nat"))
    except Exception:
        pytest.skip("native toolchain unavailable")
    partition_float3_file_np(str(inp), 4, str(tmp_path / "np"))
    for r in range(4):
        a = (tmp_path / f"nat_{r:06d}.float3").read_bytes()
        b = (tmp_path / f"np_{r:06d}.float3").read_bytes()
        assert a == b, f"part {r} differs between native and numpy"


def test_parts_are_spatially_coherent(tmp_path):
    """Each part's bounding box should be much smaller than the global box —
    the property the prepartitioned variant's pruning feeds on."""
    pts = random_points(8000, seed=7)
    inp = tmp_path / "in.float3"
    pts.tofile(inp)
    partition_float3_file(str(inp), 8, str(tmp_path / "p"))
    parts = _read_parts(str(tmp_path / "p"), 8)
    global_vol = np.prod(pts.max(0) - pts.min(0))
    vols = [np.prod(p.max(0) - p.min(0)) for p in parts if len(p)]
    # Z-order ranges are unions of octree cells; allow generous slack but
    # still far below "every part spans everything"
    assert np.median(vols) < 0.5 * global_vol


def test_end_to_end_partition_then_knn(tmp_path):
    """partition_main -> prepartitioned_main: full tool-chain parity run."""
    from mpi_cuda_largescaleknn_tpu.cli import partition_main
    from mpi_cuda_largescaleknn_tpu.cli.prepartitioned_main import (
        main as prepart_main,
    )

    pts = random_points(640, seed=9)
    inp = tmp_path / "in.float3"
    pts.tofile(inp)
    partition_main.main([str(inp), "-n", "8", "-o", str(tmp_path / "p")])
    prepart_main([str(tmp_path / "p.txt"), "-k", "5",
                  "-o", str(tmp_path / "d"), "--bucket-size", "16"])
    parts = _read_parts(str(tmp_path / "p"), 8)
    got = np.concatenate([
        np.fromfile(tmp_path / f"d_{r:06d}.float", np.float32)
        for r in range(8)])
    # outputs are in part order; oracle over the same ordering
    allp = np.concatenate(parts)
    assert_dist_equal(got, kth_nn_dist(allp, allp, 5))
