"""Tiered slab index (serve/slabpool.py): the beyond-HBM pool must be
bit-identical to a fully-resident engine at EVERY pool size.

Four layers of coverage:

- ``SlabSource`` cold tier: slab rows byte-equal to ``read_file_portion``
  (.float3) / the mmap .npy split / ``load_slab_rows`` — the same rows a
  routed host or the slab handoff would materialize.
- ``SlabPool`` mechanics with FAKE engines (no jax, no sleeps): LRU
  eviction order, pin-vs-evict, budget overcommit, host-tier demotion and
  cap, stall accounting under an injectable clock, prefetch-then-hit with
  zero stalls, promotion-error surfacing, faults.py-injected slow and
  failed promotions.
- ``StreamingKnnEngine`` parity: the budget matrix {1 slab, half, all}
  against one ``ResidentKnnEngine`` over the union — distances AND
  neighbor ids bitwise, tie ids included (the fixture plants coordinate
  duplicates across slab boundaries), plus max-radius, candidates-emit,
  escalation behavior, prefetch-overlap (announced a batch ahead = zero
  stalls), AOT sharing across eviction/re-promotion (compile_count flat),
  and the slow-promotion drill (stall counted, answer exact, no
  deadlock).
- Serving surface: /stats + /metrics pool counters through a real
  KnnServer, and the batcher's batch-ahead ``prefetch_hint``
  announcement.
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

K = 5


def _streaming_points():
    """600 rows, Morton-ish layout: [0:295) cluster A, [295:300) exact
    coordinate copies of rows [595:600) (B-region outliers inside the
    A-side slabs — cross-slab distance-0 ties AND boxes that overlap the
    B region, forcing escalation), [300:600) cluster B."""
    from tests.oracle import random_points

    a = random_points(295, seed=41, scale=0.4)
    b = (random_points(300, seed=42, scale=0.4) + np.float32(0.6))
    return np.concatenate([a, b[-5:], b]).astype(np.float32)


# --------------------------------------------------------------- SlabSource


class TestSlabSource:
    def test_float3_rows_byte_equal_to_read_file_portion(self, tmp_path):
        from mpi_cuda_largescaleknn_tpu.io.reader import read_file_portion
        from mpi_cuda_largescaleknn_tpu.serve.slabpool import SlabSource

        pts = _streaming_points()
        path = str(tmp_path / "pts.float3")
        pts.astype("<f4").tofile(path)
        src = SlabSource(path=path, num_slabs=4)
        assert src.n_total == len(pts) and src.dim == 3
        for s in range(4):
            want, begin, n = read_file_portion(path, s, 4)
            got = src.read(s)
            assert got.tobytes() == want.tobytes()
            assert src.bounds[s][0] == begin and n == len(pts)

    def test_npy_mmap_rows_byte_equal_to_slab_split(self, tmp_path):
        from mpi_cuda_largescaleknn_tpu.models.sharding import slab_bounds
        from mpi_cuda_largescaleknn_tpu.serve.engine import load_slab_rows
        from mpi_cuda_largescaleknn_tpu.serve.slabpool import SlabSource

        pts = _streaming_points()
        path = str(tmp_path / "pts.npy")
        np.save(path, pts)
        src = SlabSource(path=path, num_slabs=3)
        assert src.bounds == slab_bounds(len(pts), 3)
        for s in range(3):
            b, e = src.bounds[s]
            assert src.read(s).tobytes() == pts[b:e].tobytes()
            # the handoff/routed-host read path materializes the same rows
            rows, begin, _n = load_slab_rows(path, s, 3)
            assert begin == b and rows.tobytes() == src.read(s).tobytes()

    def test_float3_and_npy_sources_agree(self, tmp_path):
        from mpi_cuda_largescaleknn_tpu.serve.slabpool import SlabSource

        pts = _streaming_points()
        f3 = str(tmp_path / "pts.float3")
        npy = str(tmp_path / "pts.npy")
        pts.astype("<f4").tofile(f3)
        np.save(npy, pts)
        a = SlabSource(path=f3, num_slabs=5)
        b = SlabSource(path=npy, num_slabs=5)
        c = SlabSource(points=pts, num_slabs=5)
        for s in range(5):
            assert (a.read(s).tobytes() == b.read(s).tobytes()
                    == c.read(s).tobytes())

    def test_scan_aabbs_matches_slab_aabbs(self):
        from mpi_cuda_largescaleknn_tpu.models.sharding import slab_aabbs
        from mpi_cuda_largescaleknn_tpu.serve.slabpool import SlabSource

        pts = _streaming_points()
        src = SlabSource(points=pts, num_slabs=4)
        assert src.scan_aabbs() == slab_aabbs(pts, src.bounds)

    def test_rejects_bad_config(self):
        from mpi_cuda_largescaleknn_tpu.serve.slabpool import SlabSource

        with pytest.raises(ValueError):
            SlabSource(num_slabs=2)  # neither path nor points
        with pytest.raises(ValueError):
            SlabSource(points=np.zeros((4, 3)), num_slabs=0)


# ----------------------------------------------------------------- SlabPool


class _FakeEngine:
    def __init__(self, slab, rows, device_bytes):
        self.slab = slab
        self.host_points = rows
        self.device_bytes = device_bytes


class _PoolRig:
    """A SlabPool over fakes: injectable clock (a plain counter — no
    wall-clock, no sleeps), a per-build time cost, and a build log."""

    def __init__(self, n=80, num_slabs=8, slab_bytes=100, build_cost=0.5,
                 fail_slabs=(), **pool_kw):
        from mpi_cuda_largescaleknn_tpu.serve.slabpool import (
            SlabPool,
            SlabSource,
        )

        self.now = [0.0]
        self.built = []
        self.slab_bytes = slab_bytes
        self.build_cost = build_cost
        self.fail_slabs = set(fail_slabs)
        src = SlabSource(points=np.arange(n * 3, dtype=np.float32)
                         .reshape(n, 3), num_slabs=num_slabs)

        def factory(slab, rows, begin):
            if slab in self.fail_slabs:
                raise RuntimeError(f"build of slab {slab} exploded")
            self.now[0] += self.build_cost
            self.built.append(slab)
            return _FakeEngine(slab, rows, self.slab_bytes)

        self.pool = SlabPool(src, factory, clock=lambda: self.now[0],
                             **pool_kw)


class TestSlabPool:
    def test_lru_eviction_order(self):
        rig = _PoolRig(device_budget_bytes=200)  # budget = 2 slabs
        p = rig.pool
        p.ensure(0), p.ensure(1)
        assert p.resident_slabs() == [0, 1]
        p.ensure(2)  # 0 is LRU -> evicted
        assert p.resident_slabs() == [1, 2]
        p.ensure(1)  # refresh 1: now 2 is LRU
        p.ensure(3)
        assert p.resident_slabs() == [1, 3]
        assert p.stats()["evictions"] == 2
        p.close()

    def test_pin_blocks_eviction_and_overcommit_counted(self):
        rig = _PoolRig(device_budget_bytes=200)
        p = rig.pool
        p.ensure(0), p.ensure(1)
        p.pin([0])
        p.ensure(2)  # 0 pinned -> 1 (LRU among unpinned) evicted
        assert p.resident_slabs() == [0, 2]
        p.pin([2])
        p.ensure(3)  # both resident slabs pinned -> overcommit, no evict
        assert p.resident_slabs() == [0, 2, 3]
        assert p.stats()["overcommits"] == 1
        # releasing the pins re-enforces the budget immediately
        p.unpin([0]), p.unpin([2])
        assert len(p.resident_slabs()) == 2
        assert p.stats()["device_bytes_used"] <= 200
        p.close()

    def test_host_tier_demotion_and_cap(self):
        rig = _PoolRig(device_budget_bytes=100, host_pool_slabs=3)
        p = rig.pool
        p.ensure(0)
        p.ensure(1)  # evicts 0 -> its rows demote to the host tier
        p.ensure(2)  # evicts 1
        s = p.stats()
        assert s["cold_reads"] == 3 and s["host_resident"] == 3
        p.ensure(0)  # rows still warm in host RAM -> no cold read
        s = p.stats()
        assert s["host_hits"] == 1 and s["cold_reads"] == 3
        # cap: the host tier never exceeds host_pool_slabs, and pushing
        # enough new slabs through it evicts the oldest rows
        for slab in (3, 4, 5):
            p.ensure(slab)
        s = p.stats()
        assert s["host_resident"] <= 3 and s["host_evictions"] > 0
        # slab 1 fell out of the host tier long ago -> a cold read again
        cold_before = s["cold_reads"]
        p.ensure(1)
        assert p.stats()["cold_reads"] == cold_before + 1
        p.close()

    def test_stall_accounting_via_injectable_clock(self):
        rig = _PoolRig(device_budget_bytes=0, build_cost=0.5)
        p = rig.pool
        p.ensure(0)  # cold promote: one stall of exactly one build cost
        s = p.stats()
        assert s["stream_stalls"] == 1
        assert s["stream_stall_seconds"] == pytest.approx(0.5)
        p.ensure(0)  # resident: a device hit, no new stall
        s = p.stats()
        assert s["stream_stalls"] == 1 and s["device_hits"] == 1
        p.ensure(1, count_stall=False)  # warmup/prefetch path: no stall
        assert p.stats()["stream_stalls"] == 1
        p.close()

    def test_prefetch_then_ensure_is_stall_free(self):
        rig = _PoolRig(device_budget_bytes=0)
        p = rig.pool
        p.prefetch([3, 4])
        assert p.wait_idle(timeout_s=10)
        assert set(p.resident_slabs()) >= {3, 4}
        p.ensure(3), p.ensure(4)
        s = p.stats()
        assert s["stream_stalls"] == 0 and s["device_hits"] == 2
        assert s["prefetch_enqueued"] == 2
        p.close()

    def test_promotion_error_surfaces_and_pool_survives(self):
        rig = _PoolRig(fail_slabs={5})
        p = rig.pool
        with pytest.raises(RuntimeError, match="slab 5"):
            p.ensure(5)
        s = p.stats()
        assert s["promotion_errors"] == 1 and "slab 5" in s["last_error"]
        # the prefetch thread survives a failing slab too
        p.prefetch([5, 6])
        assert p.wait_idle(timeout_s=10)
        s = p.stats()
        assert s["prefetch_errors"] == 1 and 6 in p.resident_slabs()
        p.close()

    def test_faults_injected_slow_promotion_counts_a_stall(self):
        from mpi_cuda_largescaleknn_tpu.serve.faults import FaultInjector

        inj = FaultInjector.from_env()
        inj.set_specs("latency:path=/slab/2,delay_s=0.25")
        rig = _PoolRig(build_cost=0.0, faults=inj)
        p = rig.pool
        # injectable sleep rides the SAME fake clock — no real sleeping
        p._sleep = lambda s: rig.now.__setitem__(0, rig.now[0] + s)
        p.ensure(1)
        assert p.stats()["stream_stall_seconds"] == pytest.approx(0.0)
        p.ensure(2)  # the injected 250 ms promotion delay is a stall
        assert p.stats()["stream_stall_seconds"] == pytest.approx(0.25)
        p.close()

    def test_faults_injected_promotion_failure_raises(self):
        from mpi_cuda_largescaleknn_tpu.serve.faults import FaultInjector

        inj = FaultInjector.from_env()
        inj.set_specs("error:path=/slab/1,n=1")
        rig = _PoolRig(faults=inj)
        with pytest.raises(RuntimeError, match="injected"):
            rig.pool.ensure(1)
        rig.pool.ensure(1)  # fire budget n=1 spent -> retry succeeds
        assert 1 in rig.pool.resident_slabs()
        rig.pool.close()

    def test_concurrent_ensure_single_build(self):
        rig = _PoolRig()
        p = rig.pool
        errs = []

        def hit():
            try:
                p.ensure(2)
            except Exception as e:  # pragma: no cover - surfaced below
                errs.append(e)

        ts = [threading.Thread(target=hit) for _ in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=10)
        assert not errs
        assert rig.built.count(2) == 1  # one promotion, not four
        p.close()


# ------------------------------------------------------- streaming parity


@pytest.fixture(scope="module")
def parity_rig():
    """One fully-resident reference engine + one streaming engine over
    the same 600 points (4 slabs, shared AOT cache), both canonical."""
    from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
    from mpi_cuda_largescaleknn_tpu.serve.engine import ResidentKnnEngine
    from mpi_cuda_largescaleknn_tpu.serve.slabpool import StreamingKnnEngine

    pts = _streaming_points()
    ref = ResidentKnnEngine(pts, K, mesh=get_mesh(2), engine="tiled",
                            bucket_size=64, max_batch=32, min_batch=16,
                            merge="device")
    ref.warmup()
    stream = StreamingKnnEngine(points=pts, num_slabs=4, k=K,
                                mesh=get_mesh(2), engine="tiled",
                                bucket_size=64, max_batch=32, min_batch=16,
                                merge="device")
    stream.warmup()
    yield pts, ref, stream
    stream.close()


def _probe_batches(pts, seed=0):
    """Deterministic probe set: random batches, cluster-boundary rows,
    exact-duplicate coordinates (distance-0 cross-slab ties), and a
    single-row batch."""
    rng = np.random.default_rng(seed)
    return [
        rng.random((17, 3)).astype(np.float32),
        rng.random((32, 3)).astype(np.float32) * 1.2 - 0.1,
        pts[[0, 150, 295, 296, 299, 595, 599]],  # the planted dups
        np.full((3, 3), 0.5, np.float32),        # the A/B gap
        pts[42:43],
    ]


class TestStreamingParity:
    def test_bitwise_parity_across_budget_matrix(self, parity_rig):
        """THE acceptance bar: budgets {1 slab, half, all} all serve the
        fully-resident engine's exact bytes — dists and tie ids."""
        pts, ref, stream = parity_rig
        slab_b = stream.slab_device_bytes
        for budget_slabs in (1, 2, 4):
            stream.slab_pool.set_device_budget(slab_b * budget_slabs)
            for q in _probe_batches(pts):
                dr, nr = ref.query(q)
                ds, ns = stream.query(q)
                assert np.array_equal(np.asarray(dr, np.float32), ds), \
                    f"dists diverge at budget {budget_slabs} slabs"
                assert np.array_equal(np.asarray(nr), ns), \
                    f"tie/neighbor ids diverge at budget {budget_slabs}"
            assert (len(stream.slab_pool.resident_slabs())
                    <= max(1, budget_slabs) + 1)

    def test_deep_cluster_query_routes_away_from_far_slabs(self,
                                                           parity_rig):
        """Routing actually routes: a query deep inside cluster A never
        visits the B-side slabs (they certify away on bounds — that is
        the streaming win: far slabs need not even be resident); gap
        queries escalate across the boundary."""
        pts, _ref, stream = parity_rig
        stream.slab_pool.set_device_budget(0)
        h = stream.dispatch(pts[10:11])  # deep inside cluster A
        stream.complete(h)
        # slabs 2/3 hold cluster B (rows 300..599) — certified away
        assert h.visited[0, 2:].sum() == 0
        before = stream.timers.counter("stream_escalations")
        h2 = stream.dispatch(np.full((2, 3), 0.5, np.float32))  # the gap
        stream.complete(h2)
        assert h2.visited.sum(axis=1).max() > 1
        assert stream.timers.counter("stream_escalations") > before

    def test_pins_released_after_complete(self, parity_rig):
        pts, _ref, stream = parity_rig
        stream.query(pts[:8])
        assert stream.slab_pool.stats()["pinned_slabs"] == []

    def test_aot_shared_across_eviction_churn(self, parity_rig):
        """Recompile freedom pool-wide: cycling every slab through a
        1-slab budget reuses the shared executables — compile_count
        (the shared cache's compile counter) stays flat."""
        pts, _ref, stream = parity_rig
        before = stream.stats()["compile_count"]
        slab_b = stream.slab_device_bytes
        stream.slab_pool.set_device_budget(slab_b)  # churn everything
        for q in _probe_batches(pts):
            stream.query(q)
        stats = stream.stats()
        assert stats["compile_count"] == before
        assert stats["slab_pool"]["evictions"] > 0  # it really churned
        stream.slab_pool.set_device_budget(0)

    def test_prefetch_hint_announced_ahead_means_zero_stalls(self,
                                                             parity_rig):
        """The overlap contract: announcing the routed slab set a batch
        ahead (and letting the promotion thread land it) makes the later
        dispatch stall-free."""
        pts, _ref, stream = parity_rig
        slab_b = stream.slab_device_bytes
        # budget of 3 slabs: wide enough for one batch's full routed set
        # (slab 1's box spans both clusters — the planted outliers — so a
        # B batch routes to {1, 2, 3}), narrow enough that parking at one
        # end of the index evicts the other end's slabs
        stream.slab_pool.set_device_budget(3 * slab_b)
        q_a, q_b = pts[10:18], pts[590:598]  # opposite ends of the index
        stream.query(q_a)  # park the pool at the A end
        stream.slab_pool.wait_idle(timeout_s=30)
        stream.prefetch_hint(q_b)  # announce the B batch one batch ahead
        assert stream.slab_pool.wait_idle(timeout_s=30)
        before = stream.slab_pool.stats()["stream_stalls"]
        stream.query(q_b)
        assert stream.slab_pool.stats()["stream_stalls"] == before
        # and the un-hinted twin DOES stall after the pool moves away
        stream.query(q_a)
        stream.slab_pool.wait_idle(timeout_s=30)
        stream.query(q_b)
        assert stream.slab_pool.stats()["stream_stalls"] > before
        stream.slab_pool.set_device_budget(0)

    def test_slow_promotion_stalls_but_stays_exact(self, parity_rig):
        """faults.py latency on a promotion: the batch STALLS (counted)
        instead of deadlocking or approximating — the answer is still
        the reference's bytes."""
        from mpi_cuda_largescaleknn_tpu.serve.faults import FaultInjector

        pts, ref, stream = parity_rig
        slab_b = stream.slab_device_bytes
        stream.slab_pool.set_device_budget(slab_b)
        stream.query(pts[10:18])  # park at the A end
        stream.slab_pool.wait_idle(timeout_s=30)
        inj = FaultInjector.from_env()
        inj.set_specs("latency:path=/slab/,delay_s=0.2")
        stream.slab_pool._faults = inj
        try:
            before = stream.slab_pool.stats()
            q = pts[590:598]
            dr, nr = ref.query(q)
            ds, ns = stream.query(q)
            after = stream.slab_pool.stats()
            assert np.array_equal(np.asarray(dr, np.float32), ds)
            assert np.array_equal(np.asarray(nr), ns)
            assert after["stream_stalls"] > before["stream_stalls"]
            assert (after["stream_stall_seconds"]
                    >= before["stream_stall_seconds"] + 0.2)
        finally:
            stream.slab_pool._faults = None
            stream.slab_pool.set_device_budget(0)

    def test_dispatch_promotion_failure_releases_pins(self):
        """A failed promotion mid-dispatch must raise AND release the
        batch's pins — leaked pins would make slabs permanently
        unevictable; after the fault clears the engine serves exactly."""
        from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
        from mpi_cuda_largescaleknn_tpu.serve.faults import FaultInjector
        from mpi_cuda_largescaleknn_tpu.serve.slabpool import (
            StreamingKnnEngine,
        )
        from tests.oracle import random_points

        pts = np.sort(random_points(96, seed=3), axis=0)  # slab locality
        # prefetch_depth=0: the escalation-insurance prefetch would
        # otherwise promote the far slab in the background and the
        # deterministic fault below would never be reached
        stream = StreamingKnnEngine(points=pts, num_slabs=2, k=3,
                                    mesh=get_mesh(1), engine="tiled",
                                    bucket_size=32, max_batch=16,
                                    min_batch=8, prefetch_depth=0)
        try:
            stream.slab_pool.set_device_budget(stream.slab_device_bytes)
            stream.query(pts[:4])  # park at the low end
            stream.slab_pool.wait_idle(timeout_s=30)
            inj = FaultInjector.from_env()
            inj.set_specs("error:path=/slab/,n=2")
            stream.slab_pool._faults = inj
            with pytest.raises(RuntimeError, match="injected"):
                stream.query(pts[90:94])  # needs the evicted far slab
            assert stream.slab_pool.stats()["pinned_slabs"] == []
            inj.clear()
            d, n = stream.query(pts[90:94])  # recovers, still exact
            assert np.isfinite(d).all()
        finally:
            stream.close()

    def test_max_radius_parity(self):
        from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
        from mpi_cuda_largescaleknn_tpu.serve.engine import ResidentKnnEngine
        from mpi_cuda_largescaleknn_tpu.serve.slabpool import (
            StreamingKnnEngine,
        )
        from tests.oracle import random_points

        pts = random_points(96, seed=3)
        ref = ResidentKnnEngine(pts, 3, mesh=get_mesh(2), engine="tiled",
                                bucket_size=32, max_batch=16, min_batch=8,
                                max_radius=0.15)
        stream = StreamingKnnEngine(points=pts, num_slabs=2, k=3,
                                    mesh=get_mesh(2), engine="tiled",
                                    bucket_size=32, max_batch=16,
                                    min_batch=8, max_radius=0.15)
        try:
            q = random_points(16, seed=9)
            dr, nr = ref.query(q)
            ds, ns = stream.query(q)
            assert np.array_equal(np.asarray(dr, np.float32), ds)
            assert np.array_equal(np.asarray(nr), ns)
        finally:
            stream.close()

    def test_candidates_emit_parity(self):
        """emit='candidates' (the routed-host wrapper): the streamed fold
        equals a resident candidates engine's rows bitwise — what a
        routed pod folds when its hosts stream sub-slabs."""
        from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
        from mpi_cuda_largescaleknn_tpu.serve.engine import ResidentKnnEngine
        from mpi_cuda_largescaleknn_tpu.serve.slabpool import (
            StreamingKnnEngine,
        )
        from tests.oracle import random_points

        pts = random_points(128, seed=5)
        ref = ResidentKnnEngine(pts, 4, mesh=get_mesh(2), engine="tiled",
                                bucket_size=32, max_batch=16, min_batch=8,
                                id_offset=1000, emit="candidates")
        stream = StreamingKnnEngine(points=pts, num_slabs=3, k=4,
                                    mesh=get_mesh(2), engine="tiled",
                                    bucket_size=32, max_batch=16,
                                    min_batch=8, id_offset=1000,
                                    emit="candidates")
        try:
            q = random_points(12, seed=11)
            dr, nr = ref.complete_candidates(ref.dispatch(q))
            ds, ns = stream.complete_candidates(stream.dispatch(q))
            assert np.array_equal(np.asarray(dr), ds)
            assert np.array_equal(np.asarray(nr), ns)
            with pytest.raises(RuntimeError, match="complete_candidates"):
                stream.complete(stream.dispatch(q))
        finally:
            stream.close()

    def test_empty_batch(self, parity_rig):
        _pts, _ref, stream = parity_rig
        d, n = stream.query(np.zeros((0, 3), np.float32))
        assert d.shape == (0,) and n.shape == (0, K)


# -------------------------------------------------------- serving surface


class TestStreamingServing:
    def test_stats_and_metrics_surface(self, parity_rig):
        from mpi_cuda_largescaleknn_tpu.serve.server import build_server

        _pts, _ref, stream = parity_rig
        stats = stream.stats()
        pool = stats["slab_pool"]
        for key in ("device_resident", "host_resident", "promotions",
                    "evictions", "stream_stalls", "stream_stall_seconds",
                    "device_hits", "host_hits", "cold_reads",
                    "device_budget_bytes", "slab_device_bytes"):
            assert key in pool, key
        assert stats["device_bytes"] == (stream.slab_device_bytes
                                         * pool["device_resident"])
        srv = build_server(stream, port=0)
        try:
            from mpi_cuda_largescaleknn_tpu.serve.server import _Handler

            text = _Handler._prometheus(srv)
            for line in ('knn_slab_pool_resident{tier="device"}',
                         'knn_slab_pool_resident{tier="host"}',
                         "knn_slab_promotions_total",
                         "knn_slab_evictions_total",
                         "knn_stream_stall_seconds_total",
                         'knn_slab_pool_hits_total{tier="device"}',
                         "knn_slab_pool_cold_reads_total"):
                assert line in text, line
        finally:
            srv.close()

    def test_served_e2e_oracle_exact(self, parity_rig):
        """Full HTTP stack over the streaming engine at a 2-slab budget:
        batcher + admission + server, answers equal to brute force."""
        import json
        import urllib.request

        from mpi_cuda_largescaleknn_tpu.serve.server import build_server
        from tests.oracle import kth_nn_dist

        pts, _ref, stream = parity_rig
        stream.slab_pool.set_device_budget(stream.slab_device_bytes * 2)
        srv = build_server(stream, port=0, pipeline_depth=2)
        srv.ready = True
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{srv.server_address[1]}"
        try:
            rng = np.random.default_rng(0)
            q = rng.random((24, 3)).astype(np.float32)
            req = urllib.request.Request(
                base + "/knn",
                data=json.dumps({"queries": q.tolist()}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=120) as resp:
                got = np.asarray(json.loads(resp.read())["dists"],
                                 np.float32)
            want = kth_nn_dist(q, pts, K)
            assert np.allclose(got, want, rtol=5e-7, atol=1e-37)
        finally:
            srv.close()
            stream.slab_pool.set_device_budget(0)


class TestBatcherPrefetchHint:
    def test_queued_rows_announced_after_dispatch(self):
        """``_announce_prefetch`` forwards the still-QUEUED rows — the
        next batch's content, capped at max_batch — to the query_fn's
        ``prefetch_hint`` (deterministic unit drive: the queue is staged
        directly, no worker races)."""
        import time as _time

        from mpi_cuda_largescaleknn_tpu.serve.batcher import (
            DynamicBatcher,
            _Request,
        )

        hinted = []

        class _Fn:
            dim = 3

            def dispatch(self, q):
                return np.asarray(q)

            def complete(self, handle):
                n = len(handle)
                return np.zeros(n, np.float32), np.zeros((n, 2), np.int32)

            def prefetch_hint(self, q):
                hinted.append(np.asarray(q).copy())

        b = DynamicBatcher(_Fn(), max_batch=4, max_delay_s=60.0,
                           pipeline_depth=2)
        try:
            assert b._prefetch_fn is not None  # wired through
            now = _time.monotonic()
            with b._cond:
                for i in range(3):  # 6 rows queued > max_batch 4
                    b._queue.append(_Request(
                        queries=np.full((2, 3), i, np.float32),
                        deadline=None, enqueued=now))
            b._announce_prefetch()
            assert len(hinted) == 1
            # capped at max_batch whole requests: 2 of the 3 (4 rows)
            assert hinted[0].shape == (4, 3)
            assert np.array_equal(hinted[0][:2],
                                  np.zeros((2, 3), np.float32))
            # empty queue -> no announcement
            with b._cond:
                b._queue.clear()
            b._announce_prefetch()
            assert len(hinted) == 1
            assert b.stats()["prefetch_hint_errors"] == 0
        finally:
            with b._cond:
                b._queue.clear()
                b._cond.notify_all()
            b.shutdown()

    def test_hint_errors_counted_not_fatal(self):
        from mpi_cuda_largescaleknn_tpu.serve.batcher import DynamicBatcher

        release = threading.Event()

        class _Fn:
            dim = 3

            def dispatch(self, q):
                return np.asarray(q)

            def complete(self, handle):
                release.wait(10)
                n = len(handle)
                return (np.zeros(n, np.float32),
                        np.zeros((n, 2), np.int32))

            def prefetch_hint(self, q):
                raise RuntimeError("hint exploded")

        b = DynamicBatcher(_Fn(), max_batch=8, max_delay_s=0.001,
                           pipeline_depth=2)
        try:
            out = []
            ts = [threading.Thread(
                target=lambda i=i: out.append(
                    b.submit(np.full((2, 3), i, np.float32))))
                for i in range(4)]
            for t in ts:
                t.start()
            release.set()
            for t in ts:
                t.join(timeout=10)
            assert len(out) == 4  # every batch still answered
        finally:
            b.shutdown()
