"""lskcheck analyzer tests: every rule class catches its violation, the
waiver grammar is enforced, lock-order inversions are found, the AOT
contract diff detects drift — and the repo itself gates clean.

Fixture snippets are inline sources run through the same pipeline the
CLI uses (analysis/runner.py), so what the tests prove is exactly what
CI enforces.
"""

from __future__ import annotations

import copy
import json
import os

import pytest

from mpi_cuda_largescaleknn_tpu.analysis.findings import RULES, Report
from mpi_cuda_largescaleknn_tpu.analysis.locks import (
    check_lock_discipline,
    lock_order_findings,
    resolve_inheritance,
)
from mpi_cuda_largescaleknn_tpu.analysis.runner import (
    DEFAULT_ROOTS,
    analyze_source,
    apply_waivers,
    discover_files,
    repo_root,
    run_files,
)
from mpi_cuda_largescaleknn_tpu.analysis.waivers import parse_waivers


def check_snippet(src: str):
    """Full pipeline over one in-memory module; returns all findings."""
    findings, classes, waivers = analyze_source(src, "snippet.py")
    resolve_inheritance(classes)
    findings += check_lock_discipline(classes, {"snippet.py": waivers})
    order, _edges = lock_order_findings(classes)
    findings += order
    apply_waivers(findings, {"snippet.py": waivers})
    return findings


def unwaived(findings, rule=None):
    return [f for f in findings
            if not f.waived and (rule is None or f.rule == rule)]


def waived(findings, rule):
    return [f for f in findings if f.waived and f.rule == rule]


# ---------------------------------------------------------------- waivers


class TestWaiverGrammar:
    def test_trailing_allow(self):
        t = parse_waivers("x = time.time()  # lsk: allow[wallclock] bench\n",
                          "f.py")
        assert t.waiver_for("wallclock", 1) == "bench"
        assert not t.errors

    def test_standalone_allow_covers_next_line(self):
        src = "# lsk: allow[wallclock] bench only\nx = time.time()\n"
        t = parse_waivers(src, "f.py")
        assert t.waiver_for("wallclock", 2) == "bench only"
        assert t.waiver_for("wallclock", 1) is None

    def test_missing_reason_is_a_finding(self):
        t = parse_waivers("x = 1  # lsk: allow[wallclock]\n", "f.py")
        assert len(t.errors) == 1 and t.errors[0].rule == "waiver"
        assert t.waiver_for("wallclock", 1) is None

    def test_unknown_rule_is_a_finding(self):
        t = parse_waivers("x = 1  # lsk: allow[not-a-rule] because\n",
                          "f.py")
        assert len(t.errors) == 1
        assert "not-a-rule" in t.errors[0].message

    def test_multi_rule_allow(self):
        t = parse_waivers(
            "x = 1  # lsk: allow[wallclock,float-eq] twin reasons\n", "f.py")
        assert t.waiver_for("wallclock", 1) and t.waiver_for("float-eq", 1)

    def test_garbled_directive_is_a_finding(self):
        t = parse_waivers("x = 1  # lsk: allwo[wallclock] typo\n", "f.py")
        assert len(t.errors) == 1

    def test_string_literal_not_a_directive(self):
        t = parse_waivers('x = "# lsk: allow[wallclock] nope"\n', "f.py")
        assert not t.allows and not t.errors

    def test_holds_parses(self):
        src = "def f(self):  # lsk: holds[_lock]\n    pass\n"
        t = parse_waivers(src, "f.py")
        assert t.holds_for(1) == ["_lock"]


# ----------------------------------------------------------- determinism


class TestDeterminismRules:
    def test_wallclock_violation(self):
        fs = check_snippet("import time\nt = time.time()\n")
        assert len(unwaived(fs, "wallclock")) == 1

    def test_wallclock_waived(self):
        fs = check_snippet(
            "import time\n"
            "t = time.time()  # lsk: allow[wallclock] epoch for a report\n")
        assert not unwaived(fs)
        assert waived(fs, "wallclock")

    def test_wallclock_datetime_today(self):
        fs = check_snippet(
            "import datetime\n"
            "a = datetime.datetime.now()\n"
            "b = datetime.date.today()\n")
        assert len(unwaived(fs, "wallclock")) == 2

    def test_wallclock_clean(self):
        fs = check_snippet(
            "import time\nt = time.perf_counter()\nu = time.monotonic()\n")
        assert not unwaived(fs, "wallclock")

    def test_rng_global_stream(self):
        fs = check_snippet("import random\nx = random.random()\n")
        assert len(unwaived(fs, "rng-unseeded")) == 1

    def test_rng_unseeded_constructors(self):
        fs = check_snippet(
            "import random\nimport numpy as np\n"
            "a = random.Random()\nb = np.random.default_rng()\n"
            "c = np.random.rand(3)\n")
        assert len(unwaived(fs, "rng-unseeded")) == 3

    def test_rng_seeded_clean(self):
        fs = check_snippet(
            "import random\nimport numpy as np\n"
            "a = random.Random(7)\nb = np.random.default_rng(0)\n"
            "c = np.random.default_rng((1, 2))\n")
        assert not unwaived(fs, "rng-unseeded")

    def test_float_eq_on_distances(self):
        fs = check_snippet("ok = d2 == kth\n")
        assert len(unwaived(fs, "float-eq")) == 1

    def test_float_eq_literal(self):
        fs = check_snippet("ok = x == 0.5\n")
        assert len(unwaived(fs, "float-eq")) == 1

    def test_float_eq_string_config_clean(self):
        fs = check_snippet('ok = score_dtype == "f32"\n'
                           "none_ok = max_radius == None\n")
        assert not unwaived(fs, "float-eq")

    def test_float_eq_waived(self):
        fs = check_snippet(
            "tied = d2 == kth  # lsk: allow[float-eq] bitwise tie class\n")
        assert not unwaived(fs) and waived(fs, "float-eq")

    def test_argsort_unstable(self):
        fs = check_snippet("import numpy as np\no = np.argsort(d2)\n")
        assert len(unwaived(fs, "sort-unstable")) == 1

    def test_argsort_stable_clean(self):
        fs = check_snippet(
            "import numpy as np\no = np.argsort(d2, kind='stable')\n")
        assert not unwaived(fs, "sort-unstable")

    def test_np_value_sort_clean(self):
        # plain value sorts are order-deterministic; only argsort carries
        # ids that ties can scramble
        fs = check_snippet("import numpy as np\no = np.sort(d2, axis=1)\n")
        assert not unwaived(fs, "sort-unstable")

    def test_lax_sort_single_key_unstable(self):
        fs = check_snippet(
            "from jax import lax\no = lax.sort((d2, idx), num_keys=1)\n")
        assert len(unwaived(fs, "sort-unstable")) == 1

    def test_lax_sort_two_key_clean(self):
        # the (dist2, id) pair is a total order: stability is irrelevant
        fs = check_snippet(
            "from jax import lax\n"
            "o = lax.sort((d2, idx), num_keys=2)\n"
            "p = lax.sort((d2, idx), num_keys=1, is_stable=True)\n")
        assert not unwaived(fs, "sort-unstable")

    def test_dict_order_fold(self):
        fs = check_snippet(
            "def fold_hosts(parts):\n"
            "    acc = 0.0\n"
            "    for p in parts.values():\n"
            "        acc += p\n"
            "    return acc\n")
        assert len(unwaived(fs, "dict-order-fold")) == 1

    def test_dict_order_fold_sorted_clean(self):
        fs = check_snippet(
            "def fold_hosts(parts):\n"
            "    acc = 0.0\n"
            "    for _k, p in sorted(parts.items()):\n"
            "        acc += p\n"
            "    return acc\n")
        assert not unwaived(fs, "dict-order-fold")

    def test_except_swallow(self):
        fs = check_snippet(
            "try:\n    x = 1\nexcept Exception:\n    pass\n")
        assert len(unwaived(fs, "except-swallow")) == 1

    def test_bare_except_swallow(self):
        fs = check_snippet(
            "try:\n    x = 1\nexcept:\n    pass\n")
        assert len(unwaived(fs, "except-swallow")) == 1

    def test_except_counted_clean(self):
        fs = check_snippet(
            "try:\n    x = 1\n"
            "except Exception as e:\n"
            "    errors += 1\n    last = str(e)\n")
        assert not unwaived(fs, "except-swallow")

    def test_narrow_except_clean(self):
        fs = check_snippet(
            "try:\n    x = 1\nexcept ValueError:\n    pass\n")
        assert not unwaived(fs, "except-swallow")


# ----------------------------------------------------------------- locks

_LOCKED_CLASS = """
import threading
from mpi_cuda_largescaleknn_tpu.analysis import guarded_by

class Ctr:
    def __init__(self):
        self._lock = threading.Lock()
        self.n: guarded_by("_lock") = 0

    def inc(self):
        with self._lock:
            self.n += 1
"""


class TestLockDiscipline:
    def test_clean_class(self):
        assert not unwaived(check_snippet(_LOCKED_CLASS))

    def test_unguarded_read(self):
        fs = check_snippet(_LOCKED_CLASS + """
    def peek(self):
        return self.n
""")
        bad = unwaived(fs, "lock-guard")
        assert len(bad) == 1 and "peek" in bad[0].message

    def test_unguarded_write(self):
        fs = check_snippet(_LOCKED_CLASS + """
    def reset(self):
        self.n = 0
""")
        assert len(unwaived(fs, "lock-guard")) == 1

    def test_waived_unguarded_read(self):
        fs = check_snippet(_LOCKED_CLASS + """
    def peek(self):
        return self.n  # lsk: allow[lock-guard] racy gauge is fine here
""")
        assert not unwaived(fs) and waived(fs, "lock-guard")

    def test_init_exempt(self):
        # __init__ both declares and initializes without the lock: fine
        assert not unwaived(check_snippet(_LOCKED_CLASS), "lock-guard")

    def test_condition_counts_as_lock(self):
        fs = check_snippet("""
import threading
from mpi_cuda_largescaleknn_tpu.analysis import guarded_by

class Q:
    def __init__(self):
        self._cond = threading.Condition()
        self.depth: guarded_by("_cond") = 0

    def push(self):
        with self._cond:
            self.depth += 1
            self._cond.notify_all()
""")
        assert not unwaived(fs)

    def test_lambda_body_is_checked(self):
        # closures escape the region they're defined in — a guarded read
        # inside a lambda is checked as lock-free even under the with
        fs = check_snippet("""
import threading
from mpi_cuda_largescaleknn_tpu.analysis import guarded_by

class M:
    def __init__(self):
        self._lock = threading.Lock()
        self.rows: guarded_by("_lock") = 0

    def f(self, pool):
        pool.submit(lambda: self.rows)
""")
        assert len(unwaived(fs, "lock-guard")) == 1

    def test_subclass_inherits_guards(self):
        fs = check_snippet(_LOCKED_CLASS + """
class Sub(Ctr):
    def bad(self):
        return self.n

    def good(self):
        with self._lock:
            return self.n
""")
        bad = unwaived(fs, "lock-guard")
        assert len(bad) == 1 and "Sub.n" in bad[0].message

    def test_holds_contract(self):
        fs = check_snippet(_LOCKED_CLASS + """
    def _bump(self):  # lsk: holds[_lock]
        self.n += 1

    def good_call(self):
        with self._lock:
            self._bump()

    def bad_call(self):
        self._bump()
""")
        bad = unwaived(fs, "lock-holds")
        assert len(bad) == 1 and "bad_call" in bad[0].message
        # _bump's body itself is clean (checked as if the lock were held)
        assert not unwaived(fs, "lock-guard")


_INVERSION = """
import threading

class A:
    def __init__(self):
        self._la = threading.Lock()

    def with_both(self, b):
        with self._la:
            b.locked_op()

    def locked_op(self):
        with self._la:
            pass

class B:
    def __init__(self):
        self._lb = threading.Lock()

    def with_both(self, a):
        with self._lb:
            a.locked_op()

    def locked_op(self):
        with self._lb:
            pass
"""


class TestLockOrder:
    def test_inversion_detected(self):
        fs = check_snippet(_INVERSION)
        cyc = unwaived(fs, "lock-order")
        assert len(cyc) == 1
        assert "A._la" in cyc[0].message and "B._lb" in cyc[0].message

    def test_consistent_order_clean(self):
        fs = check_snippet("""
import threading

class A:
    def __init__(self):
        self._la = threading.Lock()

    def f(self, b):
        with self._la:
            b.g2()

class B:
    def __init__(self):
        self._lb = threading.Lock()

    def g2(self):
        with self._lb:
            pass
""")
        assert not unwaived(fs, "lock-order")

    def test_plain_lock_reacquire_is_self_deadlock(self):
        fs = check_snippet("""
import threading

class M:
    def __init__(self):
        self._lock = threading.Lock()

    def f(self):
        with self._lock:
            with self._lock:
                pass
""")
        hits = unwaived(fs, "lock-order")
        assert len(hits) == 1
        assert "self-deadlock" in hits[0].message

    def test_rlock_reacquire_is_legal_and_keeps_outer_hold(self):
        # the inner with must neither flag (RLock nests) nor release the
        # OUTER hold on exit: the guarded access after it is still locked
        fs = check_snippet("""
import threading
from mpi_cuda_largescaleknn_tpu.analysis import guarded_by

class M:
    def __init__(self):
        self._lock = threading.RLock()
        self.n: guarded_by("_lock") = 0

    def outer(self):
        with self._lock:
            self.inner()
            self.n += 1

    def inner(self):
        with self._lock:
            self.n += 1
""")
        assert not unwaived(fs, "lock-order")
        assert not unwaived(fs, "lock-guard")

    def test_semaphore_reacquire_not_flagged(self):
        # Semaphore(n>=2) may legally be acquired twice by one thread —
        # the count is invisible statically, so no deadlock claim
        fs = check_snippet("""
import threading

class M:
    def __init__(self):
        self._slots = threading.Semaphore(2)

    def f(self):
        with self._slots:
            with self._slots:
                pass
""")
        assert not unwaived(fs, "lock-order")

    def test_lock_reacquire_under_holds_contract(self):
        fs = check_snippet("""
import threading

class M:
    def __init__(self):
        self._lock = threading.Lock()

    def helper(self):  # lsk: holds[_lock]
        with self._lock:
            pass
""")
        hits = unwaived(fs, "lock-order")
        assert len(hits) == 1
        assert "helper" in hits[0].message

    def test_direct_nesting_edge(self):
        fs = check_snippet("""
import threading

class M:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def ab(self):
        with self._a:
            with self._b:
                pass

    def ba(self):
        with self._b:
            with self._a:
                pass
""")
        assert len(unwaived(fs, "lock-order")) == 1


# ------------------------------------------------------------- the repo


class TestRepoGate:
    def test_missing_root_fails_loudly(self):
        """A typo'd/renamed root must error, not gate vacuously green."""
        with pytest.raises(FileNotFoundError, match="does not exist"):
            discover_files(("no_such_package",))

    def test_repo_ast_passes_clean(self):
        """THE acceptance bar: zero unwaived findings over the package +
        tools, with every waiver carrying a reason."""
        report = run_files(discover_files(DEFAULT_ROOTS))
        assert report.ok, "\n".join(
            f.render() for f in report.unwaived)
        for f in report.findings:
            if f.waived:
                assert f.waiver_reason

    def test_serve_shared_state_is_annotated(self):
        """The convention is load-bearing: the serving modules must keep
        declaring their shared state (an empty guard table would make the
        lock pass vacuous)."""
        from mpi_cuda_largescaleknn_tpu.analysis.locks import collect_classes
        import ast

        base = repo_root()
        want = {
            "serve/admission.py": {"AdmissionController"},
            "serve/batcher.py": {"DynamicBatcher"},
            "serve/engine.py": {"ResidentKnnEngine"},
            "serve/faults.py": {"FaultInjector"},
            "serve/frontend.py": {"PodFanout", "RoutedPodFanout",
                                  "HostSliceServer"},
            "serve/health.py": {"HostHealth", "HealthMonitor"},
            "serve/qcache.py": {"QueryCache", "SeedPool"},
            "serve/recall.py": {"RecallPolicy"},
            "serve/replica.py": {"ReplicaSet", "ReplicaManager"},
            "serve/server.py": {"ServingMetrics"},
            "serve/slabpool.py": {"SlabPool", "StreamingKnnEngine"},
            "serve/tenancy.py": {"TenantRegistry", "TenantQuotas"},
            "serve/wire.py": {"WireNegotiator", "WireStats"},
        }
        for rel, expected in want.items():
            path = os.path.join(base, "mpi_cuda_largescaleknn_tpu", rel)
            tree = ast.parse(open(path).read())
            got = {c.name for c in collect_classes(tree, rel) if c.guarded}
            missing = expected - got
            assert not missing, f"{rel}: classes lost guarded_by: {missing}"

    def test_repo_lock_order_graph_acyclic(self):
        report = run_files(discover_files(DEFAULT_ROOTS))
        assert not [f for f in report.findings if f.rule == "lock-order"]
        # the graph is not empty — the passes do see real nesting
        assert report.lock_order_edges


# ------------------------------------------------------------------ AOT


@pytest.fixture(scope="module")
def contract():
    from mpi_cuda_largescaleknn_tpu.analysis.aot import trace_contract

    return trace_contract()


class TestAotContract:
    def test_golden_matches_traced(self, contract):
        """Drift gate: the committed golden equals what the fixture
        engines trace TODAY — any engine change that moves a signature
        must regenerate the golden in the same commit."""
        from mpi_cuda_largescaleknn_tpu.analysis.aot import (
            CONTRACT_RELPATH,
            diff_contract,
        )

        golden = os.path.join(repo_root(), CONTRACT_RELPATH)
        findings = diff_contract(contract, golden)
        assert not findings, "\n".join(f.message for f in findings)

    def test_signature_drift_detected(self, contract, tmp_path):
        from mpi_cuda_largescaleknn_tpu.analysis.aot import (
            diff_contract,
            write_contract,
        )

        mutated = copy.deepcopy(contract)
        cfg = mutated["configs"][0]
        pk = sorted(cfg["programs"])[0]
        cfg["programs"][pk]["out"][0] = cfg["programs"][pk]["out"][0].replace(
            "float32", "bfloat16")
        golden = tmp_path / "golden.json"
        write_contract(mutated, str(golden))
        findings = diff_contract(contract, str(golden))
        assert any("signature drifted" in f.message for f in findings)
        assert all(f.rule == "aot-contract" for f in findings)

    def test_missing_program_detected(self, contract, tmp_path):
        from mpi_cuda_largescaleknn_tpu.analysis.aot import (
            diff_contract,
            write_contract,
        )

        mutated = copy.deepcopy(contract)
        cfg = mutated["configs"][0]
        pk = sorted(cfg["programs"])[0]
        extra = dict(cfg["programs"][pk])
        cfg["programs"]["q1024|B9"] = extra
        golden = tmp_path / "golden.json"
        write_contract(mutated, str(golden))
        findings = diff_contract(contract, str(golden))
        assert any("gone" in f.message for f in findings)

    def test_missing_config_detected(self, contract, tmp_path):
        from mpi_cuda_largescaleknn_tpu.analysis.aot import (
            diff_contract,
            write_contract,
        )

        mutated = copy.deepcopy(contract)
        dropped = mutated["configs"].pop()
        golden = tmp_path / "golden.json"
        write_contract(mutated, str(golden))
        findings = diff_contract(contract, str(golden))
        assert any(dropped["key"] in f.message for f in findings)

    def test_bucket_geometry_drift_detected(self, contract, tmp_path):
        from mpi_cuda_largescaleknn_tpu.analysis.aot import (
            diff_contract,
            write_contract,
        )

        mutated = copy.deepcopy(contract)
        mutated["configs"][0]["query_buckets"]["8"] = 99
        golden = tmp_path / "golden.json"
        write_contract(mutated, str(golden))
        findings = diff_contract(contract, str(golden))
        assert any("query_buckets" in f.message for f in findings)

    def test_missing_golden_is_a_finding(self, contract, tmp_path):
        from mpi_cuda_largescaleknn_tpu.analysis.aot import diff_contract

        findings = diff_contract(contract, str(tmp_path / "absent.json"))
        assert len(findings) == 1 and "missing" in findings[0].message

    def test_contract_is_deterministic(self, contract):
        """Shapes must be a pure function of the fixture constants —
        tracing twice yields identical JSON."""
        from mpi_cuda_largescaleknn_tpu.analysis.aot import trace_contract

        assert json.dumps(contract, sort_keys=True) == json.dumps(
            trace_contract(), sort_keys=True)


# ------------------------------------------------------------------ misc


class TestReport:
    def test_report_json_round_trip(self, tmp_path):
        findings = check_snippet("import time\nt = time.time()\n")
        rep = Report(findings=findings, files_checked=1)
        out = tmp_path / "ANALYSIS.json"
        rep.dump_json(str(out))
        obj = json.loads(out.read_text())
        assert obj["summary"]["findings"] == 1
        assert obj["findings"][0]["rule"] == "wallclock"
        assert not obj["summary"]["ok"]

    def test_rule_registry_documented(self):
        # every rule id referenced by the passes exists in the registry
        for rule in ("lock-guard", "lock-holds", "lock-order", "wallclock",
                     "rng-unseeded", "float-eq", "sort-unstable",
                     "dict-order-fold", "except-swallow", "waiver",
                     "aot-contract"):
            assert rule in RULES
