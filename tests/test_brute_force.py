import numpy as np
import pytest

from mpi_cuda_largescaleknn_tpu.core.types import pad_points
from mpi_cuda_largescaleknn_tpu.ops.brute_force import knn_update_bruteforce
from mpi_cuda_largescaleknn_tpu.ops.candidates import extract_final_result, init_candidates

from .oracle import assert_dist_equal, kth_nn_dist, random_points




@pytest.mark.parametrize("n,k", [(100, 1), (257, 8), (1000, 33)])
def test_matches_oracle_self_query(n, k):
    pts = random_points(n)
    st = init_candidates(n, k)
    st = knn_update_bruteforce(st, pts, pts, query_tile=128, point_tile=128)
    got = np.array(extract_final_result(st))
    want = kth_nn_dist(pts, pts, k)
    assert_dist_equal(got, want)


def test_k_greater_than_n_gives_inf():
    pts = random_points(5)
    st = init_candidates(5, 8)
    st = knn_update_bruteforce(st, pts, pts)
    assert np.all(np.isinf(np.array(extract_final_result(st))))


def test_max_radius_bound():
    pts = random_points(300, seed=3)
    k = 10
    r = 0.05
    st = init_candidates(300, k, max_radius=r)
    st = knn_update_bruteforce(st, pts, pts, query_tile=64, point_tile=64)
    got = np.array(extract_final_result(st))
    want = kth_nn_dist(pts, pts, k, max_radius=r)
    assert_dist_equal(got, want)


def test_incremental_rounds_equal_one_shot():
    # stationary heaps + two tree shards folded in sequentially == all at once
    pts = random_points(400, seed=5)
    q = random_points(120, seed=6)
    k = 7
    one = knn_update_bruteforce(init_candidates(120, k), q, pts,
                                query_tile=64, point_tile=64)
    st = init_candidates(120, k)
    st = knn_update_bruteforce(st, q, pts[:150], query_tile=64, point_tile=64)
    st = knn_update_bruteforce(st, q, pts[150:],
                               point_ids=np.arange(150, 400, dtype=np.int32),
                               query_tile=64, point_tile=64)
    np.testing.assert_array_equal(np.array(one.dist2), np.array(st.dist2))


def test_sentinel_padding_is_inert():
    pts = random_points(100, seed=9)
    padded, _ = pad_points(pts, 160)
    k = 4
    st_pad = knn_update_bruteforce(init_candidates(100, k), pts, padded,
                                   query_tile=32, point_tile=32)
    st_ref = knn_update_bruteforce(init_candidates(100, k), pts, pts,
                                   query_tile=32, point_tile=32)
    np.testing.assert_array_equal(np.array(st_pad.dist2), np.array(st_ref.dist2))
