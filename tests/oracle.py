"""Brute-force numpy oracle — ground truth for every engine.

Mirrors the reference's semantics exactly (f32 arithmetic, strict-< radius
cutoff, k-th slot stays at cutoff^2 when fewer than k neighbors exist, the
query point itself counts as its own neighbor at distance 0).
"""

import numpy as np


def random_points(n, seed=0, scale=1.0, dim=3):
    rng = np.random.default_rng(seed)
    return (rng.random((n, dim)) * scale).astype(np.float32)


def pairwise_dist2_np(queries, points):
    """D-generic squared distances, fixed left-to-right component order —
    at D=3 the exact ``(dx*dx + dy*dy) + dz*dz`` tree the engines use
    (numpy never FMA-contracts, so with the engines' opaque-one contraction
    guard this oracle now matches them BIT FOR BIT, not just to 1 ulp)."""
    q = np.asarray(queries, np.float32)
    p = np.asarray(points, np.float32)
    acc = None
    for i in range(q.shape[1]):
        di = q[:, i:i + 1] - p[None, :, i]
        acc = di * di if acc is None else acc + di * di
    return acc


def kth_nn_dist2(queries, points, k, max_radius=np.inf):
    """f32[Q] k-th smallest squared distance (or cutoff^2 if under-full)."""
    d2 = pairwise_dist2_np(queries, points)
    r = np.float32(max_radius)
    r2 = np.float32(r * r)
    out = np.empty(d2.shape[0], np.float32)
    for i, row in enumerate(d2):
        cand = np.sort(row[row < r2], kind="stable")
        out[i] = cand[k - 1] if len(cand) >= k else r2
    return out


def kth_nn_dist(queries, points, k, max_radius=np.inf):
    """The reference's final output: sqrt of the k-th smallest dist^2
    (stays inf / at the radius when under-full)."""
    return np.sqrt(kth_nn_dist2(queries, points, k, max_radius))


def assert_dist_equal(got, want):
    """Engine-vs-oracle comparison: XLA fuses a*b+c into FMA, so engine f32
    distances can differ from numpy's by 1-2 ulp. All *engines* must agree
    bit-for-bit with each other; vs this numpy oracle we allow <=2 ulp and
    require the inf pattern (under-full queries) to match exactly."""
    got = np.asarray(got)
    want = np.asarray(want)
    np.testing.assert_array_equal(np.isinf(got), np.isinf(want))
    finite = np.isfinite(want)
    np.testing.assert_allclose(got[finite], want[finite], rtol=5e-7, atol=1e-37)
