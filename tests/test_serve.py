"""Serving layer: resident engine, micro-batcher, admission, HTTP, loadgen.

One module-scoped engine (1500 points, 8 CPU devices, 4 shape buckets) backs
every test here — residency is the subsystem's point, so the tests share the
index exactly the way production traffic would.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from mpi_cuda_largescaleknn_tpu.obs.timers import LatencyHistogram, PhaseTimers
from mpi_cuda_largescaleknn_tpu.serve.admission import (
    AdmissionController,
    DeadlineExceeded,
    GracefulQueryFn,
    OverloadError,
)
from mpi_cuda_largescaleknn_tpu.serve.batcher import DynamicBatcher
from mpi_cuda_largescaleknn_tpu.serve.engine import (
    ResidentKnnEngine,
    UnservableShapeError,
)
from tests.oracle import assert_dist_equal, kth_nn_dist, random_points

K = 8
N_POINTS = 1500


@pytest.fixture(scope="module")
def index_points():
    return random_points(N_POINTS, seed=7)


@pytest.fixture(scope="module")
def engine(index_points):
    from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh

    eng = ResidentKnnEngine(index_points, K, mesh=get_mesh(8),
                            engine="tiled", bucket_size=32,
                            max_batch=128, min_batch=16)
    eng.warmup()
    return eng


@pytest.fixture(scope="module")
def server(engine):
    from mpi_cuda_largescaleknn_tpu.serve.server import build_server

    srv = build_server(engine, port=0, max_delay_s=0.002)
    srv.ready = True
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv
    srv.close()


def _post(base, payload: dict, timeout=60):
    req = urllib.request.Request(
        base + "/knn", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _url(server):
    return f"http://127.0.0.1:{server.server_address[1]}"


class TestResidentEngine:
    def test_matches_oracle_large_batch(self, engine, index_points):
        q = random_points(100, seed=99)
        d, _ = engine.query(q)
        assert_dist_equal(d, kth_nn_dist(q, index_points, K))

    def test_matches_oracle_single_query(self, engine, index_points):
        q = random_points(1, seed=3)
        d, _ = engine.query(q)
        assert_dist_equal(d, kth_nn_dist(q, index_points, K))

    def test_neighbor_ids_are_true_neighbors(self, engine, index_points):
        from tests.oracle import pairwise_dist2_np

        q = random_points(40, seed=11)
        d, nbrs = engine.query(q)
        full = pairwise_dist2_np(q, index_points)
        got_d2 = np.sort(full[np.arange(len(q))[:, None], nbrs], axis=1)
        want_d2 = np.sort(full, axis=1)[:, :K]
        np.testing.assert_allclose(got_d2, want_d2, rtol=5e-7)

    def test_recompile_freedom_across_client_batch_sizes(self, engine):
        """The ISSUE's acceptance bar: after warmup, client batches of 1, 3,
        17 and 100 must all be absorbed by shape bucketing with ZERO new
        compiles — ``compile_count`` is the engine's compile hook (it
        increments exactly when an XLA executable is built, and AOT
        executables cannot silently retrace)."""
        warm_compiles = engine.compile_count
        assert warm_compiles == len(engine.shape_buckets)
        for n in (1, 3, 17, 100):
            d, nbrs = engine.query(random_points(n, seed=n))
            assert d.shape == (n,) and nbrs.shape == (n, K)
        assert engine.compile_count == warm_compiles

    def test_bucket_selection(self, engine):
        assert engine.shape_buckets == [16, 32, 64, 128]
        assert engine.bucket_for(1) == 16
        assert engine.bucket_for(16) == 16
        assert engine.bucket_for(17) == 32
        assert engine.bucket_for(128) == 128
        with pytest.raises(UnservableShapeError):
            engine.bucket_for(129)

    def test_empty_batch(self, engine):
        d, nbrs = engine.query(np.zeros((0, 3), np.float32))
        assert d.shape == (0,) and nbrs.shape == (0, K)

    def test_bruteforce_engine_matches_oracle(self, index_points):
        from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh

        eng = ResidentKnnEngine(index_points[:300], 4, mesh=get_mesh(8),
                                engine="bruteforce", max_batch=16,
                                min_batch=16)
        q = random_points(10, seed=21)
        d, _ = eng.query(q)
        assert_dist_equal(d, kth_nn_dist(q, index_points[:300], 4))

    def test_max_radius(self, index_points):
        from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh

        r = 0.12
        eng = ResidentKnnEngine(index_points, 6, mesh=get_mesh(8),
                                engine="tiled", bucket_size=32,
                                max_radius=r, max_batch=32, min_batch=32)
        q = random_points(25, seed=33)
        d, _ = eng.query(q)
        assert_dist_equal(d, kth_nn_dist(q, index_points, 6, max_radius=r))


class TestBatcher:
    def test_coalesces_and_demuxes(self):
        seen_batches = []

        def query_fn(q):
            seen_batches.append(len(q))
            # identity-ish: dist = x coord, neighbors = row index
            return q[:, 0].copy(), np.arange(len(q), dtype=np.int32)[:, None]

        b = DynamicBatcher(query_fn, max_batch=64, max_delay_s=0.02)
        try:
            qs = [random_points(n, seed=n) for n in (3, 5, 7, 2)]
            out = [None] * len(qs)

            def call(i):
                out[i] = b.submit(qs[i], timeout_s=10)

            ths = [threading.Thread(target=call, args=(i,))
                   for i in range(len(qs))]
            for t in ths:
                t.start()
            for t in ths:
                t.join()
            for i, q in enumerate(qs):
                np.testing.assert_array_equal(out[i][0], q[:, 0])
            # the 20ms flush window must have coalesced the 4 concurrent
            # requests into fewer engine calls
            assert len(seen_batches) < len(qs)
            assert sum(seen_batches) == sum(len(q) for q in qs)
        finally:
            b.shutdown()

    def test_flushes_on_max_batch(self):
        def query_fn(q):
            return q[:, 0].copy(), np.zeros((len(q), 1), np.int32)

        b = DynamicBatcher(query_fn, max_batch=8, max_delay_s=30.0)
        try:
            # 8 rows reach max_batch -> flush long before the 30s deadline
            t0 = time.monotonic()
            got = [None, None]

            def call(i):
                got[i] = b.submit(random_points(4, seed=i), timeout_s=10)

            ths = [threading.Thread(target=call, args=(i,)) for i in (0, 1)]
            for t in ths:
                t.start()
            for t in ths:
                t.join()
            assert time.monotonic() - t0 < 5.0
            assert all(g is not None for g in got)
            assert b.stats()["flush_full"] >= 1
        finally:
            b.shutdown()

    def test_deadline_expires_in_queue(self):
        def slow_fn(q):
            time.sleep(0.15)
            return q[:, 0].copy(), np.zeros((len(q), 1), np.int32)

        b = DynamicBatcher(slow_fn, max_batch=4, max_delay_s=0.001)
        try:
            # first request occupies the worker ~150ms...
            t1 = threading.Thread(
                target=lambda: b.submit(random_points(2, seed=1),
                                        timeout_s=10))
            t1.start()
            time.sleep(0.05)
            # ...second expires while queued behind it
            with pytest.raises(DeadlineExceeded):
                b.submit(random_points(2, seed=2), timeout_s=0.02)
            t1.join()
            assert b.stats()["rows_expired"] == 2
        finally:
            b.shutdown()

    def test_errors_propagate(self):
        def bad_fn(q):
            raise RuntimeError("engine exploded")

        b = DynamicBatcher(bad_fn, max_batch=4, max_delay_s=0.001)
        try:
            with pytest.raises(RuntimeError, match="engine exploded"):
                b.submit(random_points(2, seed=1), timeout_s=5)
        finally:
            b.shutdown()


class TestPipelinedBatcher:
    """The pipelined dispatch/complete path: overlap must change throughput,
    never results, ordering, or compile counts."""

    @staticmethod
    def _submit_all(b, qs, timeout_s=60):
        out = [None] * len(qs)
        errs = [None] * len(qs)

        def call(i):
            try:
                out[i] = b.submit(qs[i], timeout_s=timeout_s)
            except Exception as e:  # noqa: BLE001 - asserted by callers
                errs[i] = e

        ths = [threading.Thread(target=call, args=(i,))
               for i in range(len(qs))]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        return out, errs

    @pytest.mark.parametrize("depth", [1, 2, 4])
    def test_oracle_exact_at_depth(self, engine, index_points, depth):
        """The ISSUE's acceptance bar: pipelined results are oracle-exact at
        every depth — the pipeline overlaps, it never reorders or mixes."""
        b = DynamicBatcher(GracefulQueryFn(engine), max_batch=engine.max_batch,
                           max_delay_s=0.002, pipeline_depth=depth)
        try:
            assert b.pipelined == (depth > 1)
            sizes = (3, 17, 30, 9, 64, 5, 40, 12, 1, 100)
            qs = [random_points(n, seed=300 + n) for n in sizes]
            out, errs = self._submit_all(b, qs)
            assert all(e is None for e in errs), errs
            for q, (d, nbrs) in zip(qs, out):
                assert_dist_equal(d, kth_nn_dist(q, index_points, K))
                assert nbrs.shape == (len(q), K)
            assert b.inflight_batches() == 0 and b.inflight_rows() == 0
        finally:
            b.shutdown()

    def test_dispatch_complete_equals_query(self, engine):
        q = random_points(23, seed=41)
        want_d, want_n = engine.query(q)
        got_d, got_n = engine.complete(engine.dispatch(q))
        np.testing.assert_array_equal(want_d, got_d)
        np.testing.assert_array_equal(want_n, got_n)

    def test_compile_count_unchanged_vs_serialized(self, engine):
        """Pipelining must not change WHICH programs run: depth-2 traffic
        across every shape bucket adds zero compiles beyond warmup."""
        warm = engine.compile_count
        b = DynamicBatcher(GracefulQueryFn(engine), max_batch=engine.max_batch,
                           max_delay_s=0.001, pipeline_depth=2)
        try:
            qs = [random_points(n, seed=n) for n in (1, 3, 17, 100, 64, 33)]
            out, errs = self._submit_all(b, qs)
            assert all(e is None for e in errs), errs
        finally:
            b.shutdown()
        assert engine.compile_count == warm

    def test_ordering_preserved_under_concurrent_flushes(self):
        """Each caller gets exactly its own rows back even when many flushes
        are in flight concurrently: handles complete FIFO and demux offsets
        never cross batches. The fake tags every row with the request's
        marker value, completion is deliberately slow to force overlap."""

        class SlowEcho:
            def dispatch(self, q):
                return q

            def complete(self, q):
                time.sleep(0.005)
                return q[:, 0].copy(), np.arange(
                    len(q), dtype=np.int32)[:, None]

        b = DynamicBatcher(SlowEcho(), max_batch=32, max_delay_s=0.001,
                           pipeline_depth=3)
        try:
            qs = []
            for i in range(24):
                q = random_points(1 + (i % 5), seed=500 + i)
                q[:, 0] = i  # marker: row ownership is checkable
                qs.append(q)
            out, errs = self._submit_all(b, qs, timeout_s=30)
            assert all(e is None for e in errs), errs
            for i, (d, _nbrs) in enumerate(out):
                np.testing.assert_array_equal(d, np.full(len(qs[i]), i,
                                                         np.float32))
        finally:
            b.shutdown()

    def test_pipeline_drains_on_midstream_degradation(self):
        """Pallas dies at COMPLETION time (async errors surface at fetch)
        with several batches already in flight: every request must still get
        a correct answer via the twin replay, the pipeline must drain to
        zero occupancy, and only one degradation may be recorded."""

        class FakeHandle:
            def __init__(self, q, engine_name):
                self.queries = q
                self.engine_name = engine_name

        class FakeEngine:
            def __init__(self):
                self.engine_name = "pallas_tiled"
                self.degraded_reason = None

            def can_degrade(self):
                return self.engine_name == "pallas_tiled"

            def degrade(self, reason):
                self.degraded_reason = reason
                self.engine_name = "tiled"

            def dispatch(self, q):
                return FakeHandle(np.asarray(q), self.engine_name)

            def complete(self, h):
                time.sleep(0.002)
                if h.engine_name == "pallas_tiled":
                    raise RuntimeError("pallas runtime failure at fetch")
                return h.queries[:, 0].copy(), np.arange(
                    len(h.queries), dtype=np.int32)[:, None]

            def query(self, q):
                return self.complete(self.dispatch(q))

        fake = FakeEngine()
        g = GracefulQueryFn(fake)
        b = DynamicBatcher(g, max_batch=8, max_delay_s=0.001,
                           pipeline_depth=3)
        try:
            qs = [random_points(4, seed=600 + i) for i in range(10)]
            out, errs = self._submit_all(b, qs, timeout_s=30)
            assert all(e is None for e in errs), errs
            for q, (d, _n) in zip(qs, out):
                np.testing.assert_array_equal(d, q[:, 0])
            assert fake.engine_name == "tiled"
            assert "pallas runtime failure" in fake.degraded_reason
            assert g.failures >= 1
            assert b.inflight_batches() == 0 and b.inflight_rows() == 0
            # still serving after the drain
            q = random_points(3, seed=999)
            d, _ = b.submit(q, timeout_s=10)
            np.testing.assert_array_equal(d, q[:, 0])
        finally:
            b.shutdown()

    def test_dispatch_time_failure_degrades_too(self):
        """A failure at DISPATCH (sync lowering error) follows the same
        degrade-and-retry path as the serialized wrapper."""

        class FakeEngine:
            def __init__(self):
                self.engine_name = "pallas_tiled"
                self.degraded_reason = None

            def can_degrade(self):
                return self.engine_name == "pallas_tiled"

            def degrade(self, reason):
                self.degraded_reason = reason
                self.engine_name = "tiled"

            def dispatch(self, q):
                if self.engine_name == "pallas_tiled":
                    raise RuntimeError("lowering failed")
                return np.asarray(q)

            def complete(self, q):
                return q[:, 0].copy(), np.zeros((len(q), 1), np.int32)

        fake = FakeEngine()
        g = GracefulQueryFn(fake)
        q = random_points(4, seed=3)
        d, _ = g.complete(g.dispatch(q))
        np.testing.assert_array_equal(d, q[:, 0])
        assert fake.engine_name == "tiled" and g.failures == 1

    def test_stall_aware_flush_keeps_rows_queued_while_pipe_full(self):
        """The depth-2 regression fix (BENCH_serve.json: 68 stalls/1.57s):
        the dispatch worker reserves its pipeline slot BEFORE popping, so
        while the pipe is FULL queued requests stay in the queue —
        coalescable and deadline-cancellable — instead of being popped and
        held frozen behind the semaphore. Then everything drains exactly."""

        class GatedEcho:
            def __init__(self):
                self.release = threading.Semaphore(0)

            def dispatch(self, q):
                return q

            def complete(self, q):
                self.release.acquire()
                return q[:, 0].copy(), np.zeros((len(q), 1), np.int32)

        eng = GatedEcho()
        b = DynamicBatcher(eng, max_batch=8, max_delay_s=0.001,
                           pipeline_depth=2, min_batch=8)
        try:
            qs = [random_points(8, seed=800 + i) for i in range(4)]
            for i, q in enumerate(qs):
                q[:, 0] = i
            results = [None] * 4
            ths = [threading.Thread(
                target=lambda i=i: results.__setitem__(
                    i, b.submit(qs[i], timeout_s=30))) for i in range(4)]
            for t in ths:
                t.start()
            # two full batches dispatch and fill the pipe...
            deadline = time.monotonic() + 5
            while (b.inflight_batches() < 2
                   and time.monotonic() < deadline):
                time.sleep(0.005)
            assert b.inflight_batches() == 2
            time.sleep(0.1)  # give a buggy dispatcher time to over-pop
            st = b.stats()
            # ...and the other two 8-row (>= max_batch) flushes WAIT in the
            # queue rather than being popped into the stalled worker
            assert st["batches"] == 2, st
            assert b.queue_depth_rows() == 16
            for _ in range(4):
                eng.release.release()
            for t in ths:
                t.join(timeout=30)
            st = b.stats()
            assert st["dispatch_stalls"] >= 1
            assert st["dispatch_stall_seconds"] > 0
            for i, got in enumerate(results):
                assert got is not None
                np.testing.assert_array_equal(
                    got[0], np.full(8, i, np.float32))
        finally:
            eng.release.release()
            b.shutdown(wait=False)

    def test_busy_deadline_flush_at_min_batch(self):
        """Stall-aware flush floor: with a free pipeline slot, a deadline
        flush of >= min_batch rows dispatches while an earlier batch is
        still in flight (the old policy waited for a fully idle pipe);
        slivers below min_batch keep waiting."""

        class GatedEcho:
            def __init__(self):
                self.release = threading.Semaphore(0)

            def dispatch(self, q):
                return q

            def complete(self, q):
                self.release.acquire()
                return q[:, 0].copy(), np.zeros((len(q), 1), np.int32)

        eng = GatedEcho()
        b = DynamicBatcher(eng, max_batch=32, max_delay_s=0.001,
                           pipeline_depth=2, min_batch=8)
        try:
            out = {}
            ths = []

            def submit(tag, q):
                t = threading.Thread(
                    target=lambda: out.__setitem__(
                        tag, b.submit(q, timeout_s=30)))
                t.start()
                ths.append(t)

            submit("a", random_points(4, seed=900))  # idle pipe: flushes
            deadline = time.monotonic() + 5
            while b.inflight_batches() < 1 and time.monotonic() < deadline:
                time.sleep(0.005)
            assert b.inflight_batches() == 1
            # a 3-row sliver (< min_batch) must NOT flush while busy...
            submit("tiny", random_points(3, seed=901))
            time.sleep(0.05)
            assert b.stats()["batches"] == 1
            # ...but topping the queue up past min_batch flushes into the
            # free slot without waiting for batch a's completion
            submit("wide", random_points(10, seed=902))
            deadline = time.monotonic() + 5
            while b.stats()["batches"] < 2 and time.monotonic() < deadline:
                time.sleep(0.005)
            assert b.stats()["batches"] == 2
            assert b.inflight_batches() == 2
            for _ in range(3):
                eng.release.release()
            for t in ths:
                t.join(timeout=30)
            assert set(out) == {"a", "tiny", "wide"}
            for tag, n in (("a", 4), ("tiny", 3), ("wide", 10)):
                assert len(out[tag][0]) == n
        finally:
            eng.release.release()
            b.shutdown(wait=False)

    def test_stall_accounting_bounds_inflight(self):
        """With depth 2 and a slow completer, the dispatch worker must stall
        (bounded occupancy) and record it; occupancy never exceeds depth."""
        seen_inflight = []

        class SlowEcho:
            def __init__(self, batcher_ref):
                self._b = batcher_ref

            def dispatch(self, q):
                seen_inflight.append(self._b[0].inflight_batches())
                return q

            def complete(self, q):
                time.sleep(0.02)
                return q[:, 0].copy(), np.zeros((len(q), 1), np.int32)

        ref = [None]
        b = DynamicBatcher(SlowEcho(ref), max_batch=4, max_delay_s=0.001,
                           pipeline_depth=2)
        ref[0] = b
        try:
            qs = [random_points(4, seed=700 + i) for i in range(8)]
            out, errs = self._submit_all(b, qs, timeout_s=30)
            assert all(e is None for e in errs), errs
            st = b.stats()
            assert st["dispatch_stalls"] >= 1
            assert st["dispatch_stall_seconds"] > 0
            assert b.stall_hist.count >= 1
            assert max(seen_inflight) <= 2
        finally:
            b.shutdown()


class TestAdmission:
    def test_rejects_beyond_cap(self):
        a = AdmissionController(max_queue_rows=10)
        a.admit(8)
        with pytest.raises(OverloadError):
            a.admit(3)
        a.admit(2)  # exactly at cap is fine
        a.release(8)
        a.release(2)
        assert a.inflight_rows() == 0
        assert a.stats()["rejected"] == 1

    def test_context_manager_releases_on_error(self):
        a = AdmissionController(max_queue_rows=10)
        with pytest.raises(ValueError):
            with a.admitted_rows(10):
                raise ValueError("boom")
        assert a.inflight_rows() == 0

    def test_graceful_degradation_to_twin(self):
        class FakeEngine:
            def __init__(self):
                self.engine_name = "pallas_tiled"
                self.degraded_reason = None
                self.calls = 0

            def can_degrade(self):
                return self.engine_name == "pallas_tiled"

            def degrade(self, reason):
                self.degraded_reason = reason
                self.engine_name = "tiled"

            def query(self, q):
                self.calls += 1
                if self.engine_name == "pallas_tiled":
                    raise RuntimeError("pallas lowering failed at runtime")
                return q[:, 0], np.zeros((len(q), 1), np.int32)

        fake = FakeEngine()
        g = GracefulQueryFn(fake)
        q = random_points(4, seed=1)
        d, _ = g(q)  # first call fails in pallas, retries on the twin
        np.testing.assert_array_equal(d, q[:, 0])
        assert fake.engine_name == "tiled"
        assert "pallas lowering failed" in fake.degraded_reason
        assert g.failures == 1
        g(q)  # steady state: no more failures
        assert g.failures == 1

    def test_non_degradable_engine_reraises(self):
        class FakeEngine:
            engine_name = "tiled"

            def can_degrade(self):
                return False

            def query(self, q):
                raise RuntimeError("no fallback from here")

        with pytest.raises(RuntimeError, match="no fallback"):
            GracefulQueryFn(FakeEngine())(random_points(2, seed=1))


class TestLatencyHistogram:
    def test_percentiles_within_bucket_resolution(self):
        h = LatencyHistogram()
        vals = np.linspace(0.001, 0.100, 1000)
        for v in vals:
            h.record(float(v))
        # log buckets are ~12% wide: a quantile may be conservative by one
        # bucket, never optimistic by more than the bucket below
        for p in (50, 95, 99):
            want = float(np.percentile(vals, p))
            got = h.percentile(p)
            assert want / 1.13 <= got <= want * 1.13, (p, want, got)

    def test_report_and_empty(self):
        h = LatencyHistogram()
        assert np.isnan(h.percentile(50))
        h.record(0.01)
        rep = h.report()
        assert rep["count"] == 1 and rep["sum_seconds"] > 0

    def test_prometheus_lines_cumulative(self):
        h = LatencyHistogram()
        for v in (0.001, 0.01, 0.01, 0.1):
            h.record(v)
        lines = h.prometheus_lines("x_seconds")
        assert lines[0] == "# TYPE x_seconds histogram"
        assert 'x_seconds_bucket{le="+Inf"} 4' in lines
        assert "x_seconds_count 4" in lines

    def test_merge(self):
        a, b = LatencyHistogram(), LatencyHistogram()
        a.record(0.01)
        b.record(0.02)
        a.merge(b)
        assert a.count == 2

    def test_phase_timers_hist_in_report(self):
        t = PhaseTimers()
        t.hist("req_seconds").record(0.005)
        rep = t.report()
        assert rep["req_seconds"]["count"] == 1
        assert "p99" in rep["req_seconds"]

    def test_empty_report_is_strict_json(self):
        # an empty histogram must not leak NaN into /stats or loadgen --out:
        # json.dumps(nan) emits a non-standard token strict parsers reject
        rep = LatencyHistogram().report()
        assert rep["p50"] is None and rep["p99"] is None
        json.loads(json.dumps(rep))


class TestHTTPServing:
    def test_healthz(self, server):
        with urllib.request.urlopen(_url(server) + "/healthz", timeout=10) as r:
            assert r.status == 200
            assert json.loads(r.read())["status"] == "ok"

    def test_concurrent_clients_oracle_exact(self, server, index_points):
        """The ISSUE's end-to-end bar: concurrent clients through the full
        HTTP -> admission -> batcher -> engine -> demux path get
        oracle-exact k-th-NN distances."""
        base = _url(server)
        results = {}

        def client(i):
            q = random_points(5 + 3 * i, seed=100 + i)
            status, resp = _post(base, {"queries": q.tolist(),
                                        "neighbors": True})
            results[i] = (q, status, resp)

        ths = [threading.Thread(target=client, args=(i,)) for i in range(6)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        assert len(results) == 6
        for q, status, resp in results.values():
            assert status == 200
            assert_dist_equal(np.asarray(resp["dists"], np.float32),
                              kth_nn_dist(q, index_points, K))
            assert len(resp["neighbors"]) == len(q)

    def test_binary_roundtrip(self, server, index_points):
        q = random_points(9, seed=5)
        req = urllib.request.Request(
            _url(server) + "/knn", data=q.astype("<f4").tobytes(),
            headers={"Content-Type": "application/octet-stream"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            d = np.frombuffer(resp.read(), "<f4")
        assert_dist_equal(d, kth_nn_dist(q, index_points, K))

    def test_bad_requests(self, server):
        base = _url(server)
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(base, {"queries": [[1.0, 2.0]]})  # wrong width
        assert e.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(base, {"queries": (np.zeros((200, 3))).tolist()})  # > max
        assert e.value.code == 413

    def test_stats_and_metrics(self, server):
        base = _url(server)
        stats = json.loads(urllib.request.urlopen(
            base + "/stats", timeout=10).read())
        assert stats["engine"]["compile_count"] == len(
            stats["engine"]["shape_buckets"])
        assert stats["batcher"]["rows_served"] > 0
        m = urllib.request.urlopen(base + "/metrics", timeout=10).read().decode()
        assert "# TYPE knn_requests_total counter" in m
        assert "knn_request_latency_seconds_bucket" in m
        assert "knn_compile_count" in m

    def test_pipeline_occupancy_in_stats_and_metrics(self, server):
        """Pipeline occupancy gauges (depth, in-flight batches/rows, stall
        time) ride /stats and /metrics; the stall histogram shares the
        loadgen bucket geometry."""
        base = _url(server)
        # traffic so the pipelined path has actually run
        _post(base, {"queries": random_points(5, seed=88).tolist()})
        stats = json.loads(urllib.request.urlopen(
            base + "/stats", timeout=10).read())
        b = stats["batcher"]
        assert b["pipeline_depth"] == 2 and b["pipelined"] is True
        for key in ("inflight_batches", "inflight_rows", "dispatch_stalls",
                    "dispatch_stall_seconds"):
            assert key in b
        assert "pipeline_inflight_rows" in stats["admission"]
        m = urllib.request.urlopen(base + "/metrics",
                                   timeout=10).read().decode()
        assert "# TYPE knn_pipeline_depth gauge" in m
        assert "knn_pipeline_inflight_batches" in m
        assert "knn_pipeline_dispatch_stalls_total" in m
        # stall histogram renders even when empty (count 0, +Inf terminal)
        assert "# TYPE knn_pipeline_stall_seconds histogram" in m

    def test_no_recompiles_from_http_traffic(self, server, engine):
        """All the HTTP traffic above rode varied client batch sizes; the
        shape buckets must have absorbed every one of them."""
        assert engine.compile_count == len(engine.shape_buckets)

    def test_close_without_serve_forever_does_not_hang(self, engine):
        """Ctrl-C during warmup: close() runs before serve_forever() ever
        started — BaseServer.shutdown() would wait forever on the loop's
        event, so close() must skip it."""
        from mpi_cuda_largescaleknn_tpu.serve.server import build_server

        srv = build_server(engine, port=0)
        t = threading.Thread(target=srv.close, daemon=True)
        t.start()
        t.join(timeout=10)
        assert not t.is_alive(), "close() hung without serve_forever()"

    def test_binary_zero_rows_gets_binary_response(self, server):
        req = urllib.request.Request(
            _url(server) + "/knn", data=b"",
            headers={"Content-Type": "application/octet-stream"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"] == "application/octet-stream"
            assert resp.read() == b""

    def test_saturation_sheds_load_then_recovers(self, engine, index_points):
        """Overload: a slow engine + a 16-row admission cap + 20 concurrent
        8-row clients => most are rejected with 429 at the door; afterwards
        the server still answers correctly (stays healthy)."""
        from mpi_cuda_largescaleknn_tpu.serve.server import build_server

        real = GracefulQueryFn(engine)

        def slow_fn(q):
            time.sleep(0.08)
            return real(q)

        srv = build_server(engine, port=0, max_delay_s=0.001,
                           max_queue_rows=16, query_fn=slow_fn)
        srv.ready = True
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        base = _url(srv)
        try:
            codes = []
            lock = threading.Lock()

            def client(i):
                q = random_points(8, seed=i)
                try:
                    status, _ = _post(base, {"queries": q.tolist()})
                except urllib.error.HTTPError as e:
                    status = e.code
                with lock:
                    codes.append(status)

            ths = [threading.Thread(target=client, args=(i,))
                   for i in range(20)]
            for t in ths:
                t.start()
            for t in ths:
                t.join()
            assert codes.count(429) >= 1, codes
            assert codes.count(200) >= 1, codes
            # healthy afterwards: correct answers, zero queue
            q = random_points(4, seed=777)
            status, resp = _post(base, {"queries": q.tolist()})
            assert status == 200
            assert_dist_equal(np.asarray(resp["dists"], np.float32),
                              kth_nn_dist(q, index_points, K))
            assert srv.admission.inflight_rows() == 0
        finally:
            srv.close()


class TestLoadgen:
    def test_closed_loop_report(self, server):
        import sys

        sys.path.insert(0, "tools")
        from loadgen import run_load

        rep = run_load(_url(server), duration_s=1.0, concurrency=3, batch=8,
                       seed=1)
        assert rep["mode"] == "closed"
        assert rep["ok"] > 0 and rep["net_error"] == 0
        assert rep["qps"] > 0 and rep["rows_per_s"] > 0
        for key in ("p50_ms", "p95_ms", "p99_ms"):
            assert rep[key] > 0
        # the report must be JSON-serializable (it IS the BENCH artifact)
        json.dumps(rep)

    def test_binary_mode_and_server_stats(self, server):
        """The octet-stream wire format over the keep-alive client, plus
        the embedded /stats pipeline-occupancy scrape serve_smoke relies
        on."""
        import sys

        sys.path.insert(0, "tools")
        from loadgen import run_load

        rep = run_load(_url(server), duration_s=1.0, concurrency=2, batch=8,
                       seed=2, binary=True, server_stats=True)
        assert rep["binary"] is True
        assert rep["ok"] > 0 and rep["net_error"] == 0
        s = rep["server"]
        assert s is not None and s["pipeline_depth"] >= 1
        assert s["compile_count"] == 4  # binary traffic hit no new bucket
        json.dumps(rep)

    def test_round_robin_hosts_mode(self, server, engine):
        """--hosts: round-robin front-end-bypass across endpoints, with
        per-endpoint p50/p95/p99 so fan-out overhead is measurable."""
        import sys

        sys.path.insert(0, "tools")
        from loadgen import run_load

        from mpi_cuda_largescaleknn_tpu.serve.server import build_server

        srv2 = build_server(engine, port=0, max_delay_s=0.001)
        srv2.ready = True
        threading.Thread(target=srv2.serve_forever, daemon=True).start()
        try:
            urls = [_url(server), _url(srv2)]
            rep = run_load(urls[0], hosts=urls, duration_s=1.0,
                           concurrency=2, batch=4, seed=3)
            assert rep["endpoint_mode"] == "round_robin"
            assert set(rep["per_endpoint"]) == set(urls)
            for u in urls:
                ep = rep["per_endpoint"][u]
                assert ep["requests"] > 0 and ep["ok"] > 0
                assert ep["p50_ms"] > 0 and ep["p99_ms"] > 0
            # round-robin spreads requests evenly-ish across endpoints
            reqs = [rep["per_endpoint"][u]["requests"] for u in urls]
            assert min(reqs) > 0.25 * max(reqs)
            json.dumps(rep)
        finally:
            srv2.close()

    def test_binary_result_matches_oracle(self, server, index_points):
        """One keep-alive connection, two sequential binary posts — the
        socket is reused and both answers are exact."""
        import sys

        sys.path.insert(0, "tools")
        from loadgen import _Client

        client = _Client(_url(server), timeout_s=60)
        try:
            for seed in (21, 22):
                q = random_points(6, seed=seed)
                status, payload, _headers = client._request(
                    "/knn", np.ascontiguousarray(q, np.float32).tobytes(),
                    "application/octet-stream")
                assert status == 200
                got = np.frombuffer(payload, np.float32)
                assert_dist_equal(got, kth_nn_dist(q, index_points, K))
        finally:
            client.close()
