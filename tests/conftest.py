"""Test fixture: run everything on CPU with 8 virtual devices.

This is the TPU-world "multi-node without a cluster" fixture (SURVEY.md §4):
the ring/demand engines are exercised on a 1-D mesh of 8 host-platform
devices, standing in for the reference's `mpirun -n 8` runs.

Environment hardening: this container's sitecustomize may register an `axon`
accelerator PJRT plugin (and import jax) before this file runs. Tests must
never touch the accelerator tunnel — it is single-client and a wedged tunnel
would hang the suite — so we (a) force the platform to cpu both via env and
via jax.config (the env var alone is too late once jax is imported), and
(b) drop every non-CPU backend factory.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Import the Pallas TPU lowerings while the tpu backend factory still exists:
# register_lowering validates platforms against the currently-known backend
# set, so this must precede the factory drop below. The kernels themselves
# run in interpreter mode on CPU.
try:
    from jax.experimental.pallas import tpu as _pltpu  # noqa: E402,F401
except Exception:
    pass

try:
    from jax._src import xla_bridge as _xb

    for _name in list(getattr(_xb, "_backend_factories", {})):
        if _name != "cpu":
            _xb._backend_factories.pop(_name, None)
except Exception:
    pass
