"""Checkpoint/resume of the stepwise ring (parallel/ring.py +
utils/checkpoint.py) — a capability the reference lacks (SURVEY.md §5)."""

import numpy as np
import pytest

from mpi_cuda_largescaleknn_tpu.core.config import KnnConfig
from mpi_cuda_largescaleknn_tpu.models.sharding import pad_and_flatten, slab_bounds
from mpi_cuda_largescaleknn_tpu.models.unordered import UnorderedKNN
from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
from mpi_cuda_largescaleknn_tpu.parallel.ring import ring_knn, ring_knn_stepwise
from mpi_cuda_largescaleknn_tpu.utils import checkpoint as ckpt
from tests.oracle import assert_dist_equal, kth_nn_dist, random_points


def _sharded(pts, num_shards):
    bounds = slab_bounds(len(pts), num_shards)
    shards = [pts[b:e] for b, e in bounds]
    flat, ids, counts, npad = pad_and_flatten(
        shards, id_bases=[b for b, _ in bounds])
    return flat, ids, counts, npad


def test_stepwise_matches_fused():
    pts = random_points(520, seed=3)
    mesh = get_mesh(8)
    flat, ids, _, _ = _sharded(pts, 8)
    fused = np.asarray(ring_knn(flat, ids, 6, mesh, bucket_size=16))
    stepwise = ring_knn_stepwise(flat, ids, 6, mesh, bucket_size=16)
    np.testing.assert_array_equal(fused, stepwise)


def test_stepwise_flat_engine_matches_fused():
    pts = random_points(300, seed=5)
    mesh = get_mesh(8)
    flat, ids, _, _ = _sharded(pts, 8)
    fused = np.asarray(ring_knn(flat, ids, 4, mesh, engine="bruteforce",
                                query_tile=64, point_tile=64))
    stepwise = ring_knn_stepwise(flat, ids, 4, mesh, engine="bruteforce",
                                 query_tile=64, point_tile=64)
    np.testing.assert_array_equal(fused, stepwise)


def test_resume_from_partial_checkpoint(tmp_path):
    """Die after round 3 of the 5-round bidirectional sweep (R=8); a
    fresh run resumes there and matches the uninterrupted result
    bit-for-bit."""
    pts = random_points(480, seed=7)
    mesh = get_mesh(8)
    flat, ids, _, _ = _sharded(pts, 8)
    cdir = str(tmp_path / "ck")
    want = ring_knn_stepwise(flat, ids, 5, mesh, bucket_size=16)

    # interrupted run: only 3 of the 5 sweep rounds execute (shards seen:
    # own, +-1, +-2 of 8) before the "crash"
    partial = ring_knn_stepwise(flat, ids, 5, mesh, bucket_size=16,
                                checkpoint_dir=cdir, max_rounds=3)
    from mpi_cuda_largescaleknn_tpu.parallel.ring import resolve_engine

    # fingerprints record the RESOLVED engine (what actually computed the
    # heaps), not the "auto" alias
    fp = ckpt.fingerprint(n=int(flat.shape[0]), k=5, shards=8,
                          engine=resolve_engine("auto"),
                          max_radius=float(np.inf), bucket_size=16,
                          query_tile=2048, point_tile=2048, ring="bidir",
                          data=ckpt.data_digest(flat, ids))
    rnd, _arrs = ckpt.load_ring_state(cdir, fp)
    assert rnd == 3
    # 3 rounds cannot have visited all shards: partial must differ from final
    assert not np.array_equal(partial, want)

    # relaunch with the same args: resumes at round 3, replays 3..4
    resumed = ring_knn_stepwise(flat, ids, 5, mesh, bucket_size=16,
                                checkpoint_dir=cdir)
    np.testing.assert_array_equal(resumed, want)
    # a completed run clears its checkpoint: nothing left to resume from
    assert ckpt.load_ring_state(cdir, fp) is None


def test_checkpoint_fingerprint_mismatch_raises(tmp_path):
    pts = random_points(160, seed=9)
    mesh = get_mesh(8)
    flat, ids, _, _ = _sharded(pts, 8)
    cdir = str(tmp_path / "ck")
    # partial checkpoint at k=4 on disk...
    ring_knn_stepwise(flat, ids, 4, mesh, bucket_size=16,
                      checkpoint_dir=cdir, max_rounds=2)
    # ...must refuse to resume a k=5 run
    with pytest.raises(ValueError, match="checkpoint"):
        ring_knn_stepwise(flat, ids, 5, mesh, bucket_size=16,
                          checkpoint_dir=cdir)


def test_checkpoint_data_change_raises(tmp_path):
    """Resuming against edited input data must fail loudly, not fold new
    queries into old heaps (the data digest in the fingerprint)."""
    pts = random_points(160, seed=13)
    mesh = get_mesh(8)
    flat, ids, _, _ = _sharded(pts, 8)
    cdir = str(tmp_path / "ck")
    ring_knn_stepwise(flat, ids, 4, mesh, bucket_size=16,
                      checkpoint_dir=cdir, max_rounds=2)
    other = np.array(flat)
    other[3, 0] += 0.25  # same shape, different data
    with pytest.raises(ValueError, match="checkpoint"):
        ring_knn_stepwise(other, ids, 4, mesh, bucket_size=16,
                          checkpoint_dir=cdir)


def test_demand_stepwise_matches_fused():
    from mpi_cuda_largescaleknn_tpu.parallel.demand import (
        demand_knn,
        demand_knn_stepwise,
    )

    pts = random_points(640, seed=21)
    pts = pts[np.argsort(pts[:, 0], kind="stable")]
    mesh = get_mesh(8)
    flat, ids, _, _ = _sharded(pts, 8)
    fused, _c, fstats = demand_knn(flat, ids, 5, mesh, bucket_size=16,
                                   return_stats=True)
    step, _c2, sstats = demand_knn_stepwise(flat, ids, 5, mesh,
                                            bucket_size=16,
                                            return_stats=True)
    np.testing.assert_array_equal(np.asarray(fused), step)
    # the adaptive early exit survives the host-stepped loop
    assert int(sstats["rounds"][0]) == int(np.asarray(fstats["rounds"])[0])


def test_demand_stepwise_resume(tmp_path):
    from mpi_cuda_largescaleknn_tpu.parallel.demand import demand_knn_stepwise

    pts = random_points(480, seed=23)
    pts = pts[np.argsort(pts[:, 0], kind="stable")]
    mesh = get_mesh(8)
    flat, ids, _, _ = _sharded(pts, 8)
    cdir = str(tmp_path / "dk")
    want = demand_knn_stepwise(flat, ids, 5, mesh, bucket_size=16)
    partial = demand_knn_stepwise(flat, ids, 5, mesh, bucket_size=16,
                                  checkpoint_dir=cdir, max_rounds=2)
    del partial
    resumed = demand_knn_stepwise(flat, ids, 5, mesh, bucket_size=16,
                                  checkpoint_dir=cdir)
    np.testing.assert_array_equal(resumed, want)


def test_prepartitioned_model_checkpointed_oracle(tmp_path):
    from mpi_cuda_largescaleknn_tpu.models.prepartitioned import (
        PrePartitionedKNN,
    )

    pts = random_points(400, seed=25)
    pts = pts[np.argsort(pts[:, 0], kind="stable")]
    parts = [pts[i * 50:(i + 1) * 50] for i in range(8)]
    cfg = KnnConfig(k=4, bucket_size=16, checkpoint_dir=str(tmp_path / "p"))
    got = np.concatenate(PrePartitionedKNN(cfg, mesh=get_mesh(8)).run(parts))
    assert_dist_equal(got, kth_nn_dist(pts, pts, 4))


def test_model_level_checkpoint_and_oracle(tmp_path):
    pts = random_points(420, seed=11)
    k = 5
    cfg = KnnConfig(k=k, bucket_size=16, checkpoint_dir=str(tmp_path / "m"))
    got = UnorderedKNN(cfg, mesh=get_mesh(8)).run(pts)
    assert_dist_equal(got, kth_nn_dist(pts, pts, k))
