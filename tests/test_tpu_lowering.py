"""Cross-platform TPU lowering of the Pallas kernels — no chip needed.

``jax.export(..., platforms=["tpu"])`` runs the full JAX -> Mosaic-MLIR
frontend pipeline (layout rules, op-support checks, the dynamic-slice
rejections that round 4 could only discover on hardware) from a CPU-only
process and embeds the Mosaic payload in a ``tpu_custom_call``. It does
NOT run Mosaic's backend AOT compiler (tpu_compile_helper) — a backend
crash like the round-5 i32-row-broadcast one still needs the chip probe
(tools/tpu_probe.py) — but every *frontend* lowering regression fails
here, in CI, at the exact geometries the bench and tune sweep use.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mpi_cuda_largescaleknn_tpu.core.types import CandidateState
from mpi_cuda_largescaleknn_tpu.ops.candidates import init_candidates
from mpi_cuda_largescaleknn_tpu.ops.partition import (
    BucketedPoints,
    coarsen_buckets,
    partition_points,
)
from mpi_cuda_largescaleknn_tpu.ops.tiled import warm_start_self


def _export_tiled(n, k, bucket_size, group, warm):
    from mpi_cuda_largescaleknn_tpu.ops.pallas.knn_tiled import (
        knn_update_tiled_pallas,
    )

    rng = np.random.default_rng(0)
    pts = rng.random((n, 3)).astype(np.float32)
    q = partition_points(jnp.asarray(pts), bucket_size=bucket_size)
    pc = coarsen_buckets(q, group) if group > 1 else q
    if warm:
        st = warm_start_self(pc, k)
    else:
        st = init_candidates(q.num_buckets * q.bucket_size, k)

    def f(st_d2, st_idx, qpts, qids, ppts, pids):
        qq = BucketedPoints(qpts, qids, q.lower, q.upper, q.pos)
        pp = BucketedPoints(ppts, pids, pc.lower, pc.upper, pc.pos)
        out = knn_update_tiled_pallas(
            CandidateState(st_d2, st_idx), qq, pp, interpret=False,
            skip_self=jnp.int32(1 if warm else 0), self_group=group,
            with_stats="full")
        return out[0].dist2, out[0].idx, out[1], out[2]

    args = (st.dist2, st.idx, q.pts, q.ids, pc.pts, pc.ids)
    exp = jax.export.export(jax.jit(f), platforms=["tpu"])(*args)
    assert b"tpu_custom_call" in exp.mlir_module_serialized
    return exp


@pytest.mark.parametrize(
    "bucket_size,group,k,warm",
    [
        (256, 2, 8, True),    # auto default: the round-5 tune winner
        (512, 1, 8, True),    # round-4 default (checkpoint-compat path)
        (64, 8, 8, True),     # the tune sweep's pair-budget geometry
        (64, 8, 100, True),   # k=100: segmented fold (LSK_FOLD_SEGS path)
        (256, 1, 8, False),   # cold heap, no coarsening (probe stage shape)
    ],
)
def test_traversal_kernel_lowers_for_tpu(bucket_size, group, k, warm):
    _export_tiled(16384, k, bucket_size, group, warm)


def test_flat_kernel_lowers_for_tpu():
    from mpi_cuda_largescaleknn_tpu.ops.pallas.knn_bf import knn_update_pallas

    rng = np.random.default_rng(1)
    q = rng.random((1024, 3)).astype(np.float32)
    p = rng.random((4096, 3)).astype(np.float32)
    st = init_candidates(1024, 8)

    def f(d2, idx, q_, p_):
        out = knn_update_pallas(CandidateState(d2, idx), q_, p_,
                                query_tile=256, point_tile=2048,
                                interpret=False)
        return out.dist2, out.idx

    exp = jax.export.export(jax.jit(f), platforms=["tpu"])(
        st.dist2, st.idx, q, p)
    assert b"tpu_custom_call" in exp.mlir_module_serialized
