"""Wire codecs for the byte-heavy serving paths (serve/wire.py, PR
"quantized wire exchange").

Unit layer: pure-codec properties with no HTTP and no engine — varint /
ordered-u32 primitives, the q16 candidate codec's ONE load-bearing
invariant (``hi >= d2 >= lo`` per slot, anchor / pad / zero slots exact),
its encode-refusal preconditions (the codec returns None instead of
guessing), d16 slab losslessness down to the bit, chunk framing torn-EOF
detection, and the negotiation table (mismatch = fallback, never error).

Integration layer: one small in-process slab host booted twice — once
``wire="auto"``, once ``wire="f32"`` (the old-binary emulation) — probed
at the raw HTTP level. The acceptance bars from the issue: a q16 request
to an f32-only host falls back to plain f32 (never a decode error), the
x32 survivor re-fetch carries the exact d2 bytes, a no-``?wire=`` request
gets the pre-codec body byte-for-byte, and ``pull_slab_rows`` is lossless
across legacy / chunked-f32 / d16 paths. Plus the drift-aware
``stream_skip_cold`` admission on an injectable clock (TUNING.md's PR-16
caveat): a pool already stalling past ``skip_cold_stall_limit`` refuses
the skip plan and serves exact.
"""

from __future__ import annotations

import json
import struct
import threading
import urllib.request
import zlib

import numpy as np
import pytest

from mpi_cuda_largescaleknn_tpu.serve.wire import (
    WireError,
    WireNegotiator,
    WireStats,
    decode_candidates_q16,
    decode_slab_chunk,
    encode_candidates_q16,
    encode_slab_chunk,
    float_to_ordered_u32,
    frame_chunk,
    negotiate,
    ordered_u32_to_float,
    read_frames,
    wire_caps,
    _varint_decode,
    _varint_encode,
    _zigzag,
    _unzigzag,
)

K = 4


# ---------------------------------------------------------- primitives


class TestPrimitives:
    def test_varint_roundtrip(self):
        rng = np.random.default_rng(7)
        vals = np.concatenate([
            np.zeros(3, np.uint64),
            np.array([1, 127, 128, 16383, 16384], np.uint64),
            rng.integers(0, 2 ** 63, 200).astype(np.uint64),
            np.array([np.iinfo(np.uint64).max], np.uint64),
        ])
        raw = _varint_encode(vals)
        out, used = _varint_decode(raw, len(vals))
        assert used == len(raw)
        assert np.array_equal(out, vals)

    def test_varint_empty(self):
        assert _varint_encode(np.zeros(0, np.uint64)) == b""
        out, used = _varint_decode(b"", 0)
        assert used == 0 and out.size == 0

    def test_varint_truncated_raises(self):
        raw = _varint_encode(np.array([300, 300, 300], np.uint64))
        with pytest.raises(WireError, match="truncated"):
            _varint_decode(raw[:-1], 3)

    def test_varint_overlong_raises(self):
        with pytest.raises(WireError, match="overlong"):
            _varint_decode(b"\x80" * 10 + b"\x01", 1)

    def test_zigzag_roundtrip(self):
        v = np.array([0, -1, 1, -2 ** 62, 2 ** 62], np.int64)
        assert np.array_equal(_unzigzag(_zigzag(v)), v)

    def test_ordered_u32_is_exact_and_order_preserving(self):
        rng = np.random.default_rng(11)
        x = np.concatenate([
            rng.normal(size=500), [0.0, -0.0, 1e-38, -1e-38, 3e38, -3e38],
        ]).astype("<f4")
        u = float_to_ordered_u32(x)
        back = ordered_u32_to_float(u)
        # bit-exact inverse (−0.0 maps back to −0.0, hence view compare)
        assert np.array_equal(back.view(np.uint32), x.view(np.uint32))
        # unsigned order == float total order (−0.0 sorts just below
        # +0.0 in u32 space, which float compare calls a tie — so check
        # the float sequence sorted BY u, not u sorted by float)
        assert (np.diff(x[np.argsort(u)]) >= 0).all()


# --------------------------------------------------------- q16 candidates


def _rows(m, k, seed=0, n_valid=None, pad=np.inf):
    """Sorted candidate rows shaped like an engine partial: ascending
    d2 per row, ids a valid prefix, pads a uniform suffix."""
    rng = np.random.default_rng(seed)
    d2 = np.sort(rng.random((m, k)).astype("<f4") * 4.0, axis=1)
    idx = rng.integers(0, 10_000, (m, k)).astype("<i4")
    if n_valid is not None:
        for i, nv in enumerate(n_valid):
            d2[i, nv:] = np.float32(pad)
            idx[i, nv:] = -1
    return d2, idx


class TestQ16Codec:
    def _roundtrip(self, d2, idx):
        payload = encode_candidates_q16(d2, idx)
        assert payload is not None
        m, k = d2.shape
        hi, lo, got_idx = decode_candidates_q16(payload, m, k)
        assert np.array_equal(got_idx, idx)
        valid = idx >= 0
        # THE invariant: quantization ceils, never floors
        assert (hi[valid] >= d2[valid]).all()
        assert (lo[valid] <= d2[valid]).all()
        assert (lo <= hi).all()
        # pad slots ride exact (radius^2 / +inf verbatim)
        assert np.array_equal(hi[~valid], d2[~valid])
        assert np.array_equal(lo[~valid], d2[~valid])
        # the anchor (kth valid) slot is bit-exact — the fold's skip rule
        # and the certification radius both lean on it
        for i in range(m):
            nv = int(valid[i].sum())
            if nv:
                assert hi[i, nv - 1] == d2[i, nv - 1]
                assert lo[i, nv - 1] == d2[i, nv - 1]
        return hi, lo

    def test_full_rows_roundtrip(self):
        self._roundtrip(*_rows(64, K, seed=1))

    def test_k1_rows_are_exact(self):
        d2, idx = _rows(16, 1, seed=2)
        hi, lo = self._roundtrip(d2, idx)
        # every slot is its row's anchor: lossless end to end
        assert np.array_equal(hi, d2) and np.array_equal(lo, d2)

    def test_zero_candidate_rows(self):
        d2, idx = _rows(8, K, seed=3, n_valid=[0, 2, 0, K, 1, 0, 3, K])
        self._roundtrip(d2, idx)

    def test_all_rows_empty(self):
        d2, idx = _rows(4, K, seed=4, n_valid=[0, 0, 0, 0])
        self._roundtrip(d2, idx)

    def test_zero_row_batch(self):
        d2 = np.zeros((0, K), "<f4")
        idx = np.zeros((0, K), "<i4")
        self._roundtrip(d2, idx)

    def test_radius_truncated_rows_keep_finite_pad(self):
        # max_radius-truncated partials pad with radius^2, not +inf
        d2, idx = _rows(8, K, seed=5, n_valid=[2, 3, 1, 4, 2, 2, 3, 1],
                        pad=2.25)
        hi, lo = self._roundtrip(d2, idx)
        assert (hi[idx < 0] == np.float32(2.25)).all()

    def test_interior_slot_in_anchor_band_keeps_sound_lo(self):
        # an interior d2 within 1/65535 of the anchor also ceils to
        # level 65535; it must NOT decode to lo == anchor (the frontend
        # serves a row verbatim when every other contribution's lo
        # strictly exceeds its kth — an overstated lo drops a true
        # neighbor from a row served with exact=True)
        d2 = np.array([[9.99999, 10.0]], "<f4")
        idx = np.array([[1, 2]], "<i4")
        hi, lo = self._roundtrip(d2, idx)
        assert lo[0, 0] <= d2[0, 0]
        assert lo[0, 0] < np.float32(10.0)
        assert hi[0, 0] == np.float32(10.0)  # hi = anchor stays valid

    def test_zero_distance_slots_are_exact(self):
        d2, idx = _rows(4, K, seed=6)
        d2[:, 0] = 0.0  # exact-match neighbor
        hi, lo = self._roundtrip(d2, idx)
        assert (hi[:, 0] == 0.0).all() and (lo[:, 0] == 0.0).all()

    def test_clustered_rows_beat_f32_on_the_wire(self):
        # the codec's reason to exist: Morton-adjacent queries with
        # overlapping neighbor lists must compress well below 8mk
        rng = np.random.default_rng(8)
        m, k = 128, 16
        base = np.sort(rng.random(k).astype("<f4") * 2.0)
        d2 = np.sort(base[None, :]
                     + rng.random((m, k)).astype("<f4") * 1e-3, axis=1)
        idx = (np.arange(m)[:, None] + np.arange(k)[None, :]) \
            .astype("<i4")
        payload = encode_candidates_q16(d2, idx)
        assert payload is not None
        assert len(payload) < 0.45 * 8 * m * k

    def test_encode_refuses_k_over_255(self):
        d2 = np.zeros((2, 256), "<f4")
        idx = np.zeros((2, 256), "<i4")
        assert encode_candidates_q16(d2, idx) is None

    def test_encode_refuses_nan(self):
        d2, idx = _rows(4, K, seed=9)
        d2[1, 2] = np.nan
        assert encode_candidates_q16(d2, idx) is None

    def test_encode_refuses_non_prefix_pads(self):
        d2, idx = _rows(4, K, seed=10)
        idx[0, 1] = -1  # hole in the middle of a row
        assert encode_candidates_q16(d2, idx) is None

    def test_encode_refuses_non_uniform_pad(self):
        d2, idx = _rows(4, K, seed=11, n_valid=[2, 2, 2, 2])
        d2[0, 3] = 7.0  # two different pad distances
        assert encode_candidates_q16(d2, idx) is None

    def test_decode_rejects_shape_mismatch(self):
        d2, idx = _rows(4, K, seed=12)
        payload = encode_candidates_q16(d2, idx)
        with pytest.raises(WireError, match="mismatch"):
            decode_candidates_q16(payload, 5, K)
        with pytest.raises(WireError, match="mismatch"):
            decode_candidates_q16(payload, 4, K + 1)

    def test_decode_rejects_garbage_and_truncation(self):
        with pytest.raises(WireError):
            decode_candidates_q16(b"not zlib at all", 4, K)
        d2, idx = _rows(4, K, seed=13)
        body = zlib.decompress(encode_candidates_q16(d2, idx))
        with pytest.raises(WireError):
            decode_candidates_q16(zlib.compress(body[:-3]), 4, K)


# --------------------------------------------------------- d16 slab codec


def _morton_points(n, seed=0, scale=1.0):
    from mpi_cuda_largescaleknn_tpu.utils.math import morton_argsort

    rng = np.random.default_rng(seed)
    pts = (rng.random((n, 3)).astype(np.float32) * np.float32(scale))
    if n == 0:
        return np.zeros((0, 3), "<f4")
    order = morton_argsort(pts, pts.min(axis=0), pts.max(axis=0))
    return np.ascontiguousarray(pts[order], "<f4")


class TestD16Codec:
    @pytest.mark.parametrize("n", [0, 1, 2, 257])
    def test_lossless_roundtrip(self, n):
        pts = _morton_points(n, seed=n)
        out = decode_slab_chunk(encode_slab_chunk(pts), n, 3)
        assert np.array_equal(out.view(np.uint32), pts.view(np.uint32))

    def test_negative_coordinates_roundtrip(self):
        pts = _morton_points(128, seed=20) - np.float32(0.5)
        out = decode_slab_chunk(encode_slab_chunk(pts), 128, 3)
        assert np.array_equal(out.view(np.uint32), pts.view(np.uint32))

    def test_sign_crossing_magnitude_gt1_roundtrip(self):
        # consecutive rows crossing zero at |coord| > ~1 produce
        # zigzag'd ordered-u32 steps up to ~2^33: the width ladder must
        # widen to 8-byte planes instead of silently truncating to u32
        rng = np.random.default_rng(24)
        pts = (rng.random((4096, 3)).astype("<f4")
               * np.float32(6.0) - np.float32(3.0)).astype("<f4")
        out = decode_slab_chunk(encode_slab_chunk(pts), 4096, 3)
        assert np.array_equal(out.view(np.uint32), pts.view(np.uint32))
        # the adversarial pair alone: one maximal sign-crossing step
        pair = np.array([[-3.0, -3e38, 1e-38],
                         [3.0, 3e38, -1e-38]], "<f4")
        out = decode_slab_chunk(encode_slab_chunk(pair), 2, 3)
        assert np.array_equal(out.view(np.uint32), pair.view(np.uint32))

    def test_morton_sorted_rows_compress(self):
        pts = _morton_points(4096, seed=21, scale=0.01)
        enc = encode_slab_chunk(pts)
        assert enc[0] == 1  # took the delta path, not raw
        assert len(enc) < 0.8 * pts.nbytes

    def test_raw_fallback_chunk_decodes(self):
        pts = _morton_points(32, seed=22)
        raw = b"\x00" + pts.tobytes()
        out = decode_slab_chunk(raw, 32, 3)
        assert np.array_equal(out, pts)

    def test_decode_rejects_bad_payloads(self):
        pts = _morton_points(512, seed=23, scale=0.01)
        enc = encode_slab_chunk(pts)
        assert enc[0] == 1  # compressible fixture → delta path
        with pytest.raises(WireError):
            decode_slab_chunk(b"", 512, 3)
        with pytest.raises(WireError, match="flag"):
            decode_slab_chunk(b"\x07" + enc[1:], 512, 3)
        with pytest.raises(WireError, match="mismatch"):
            decode_slab_chunk(enc, 511, 3)
        with pytest.raises(WireError, match="mismatch"):
            decode_slab_chunk(enc, 512, 4)
        with pytest.raises(WireError):
            decode_slab_chunk(b"\x00" + pts.tobytes()[:-4], 512, 3)


class TestFraming:
    def _stream(self, chunks):
        buf = b"".join(chunks)
        pos = [0]

        def read(n):
            got = buf[pos[0]:pos[0] + n]
            pos[0] += len(got)
            return got

        return read

    def test_multi_frame_roundtrip(self):
        pts = _morton_points(100, seed=30)
        chunks = [frame_chunk(40, encode_slab_chunk(pts[:40])),
                  frame_chunk(40, encode_slab_chunk(pts[40:80])),
                  frame_chunk(20, encode_slab_chunk(pts[80:]))]
        parts = [decode_slab_chunk(payload, rows, 3)
                 for rows, payload in
                 read_frames(self._stream(chunks), 100)]
        out = np.concatenate(parts)
        assert np.array_equal(out.view(np.uint32), pts.view(np.uint32))

    def test_torn_stream_raises_not_truncates(self):
        pts = _morton_points(100, seed=31)
        whole = (frame_chunk(40, encode_slab_chunk(pts[:40]))
                 + frame_chunk(60, encode_slab_chunk(pts[40:])))
        for cut in (4, len(whole) // 2, len(whole) - 1):
            read = self._stream([whole[:cut]])
            with pytest.raises(WireError, match="torn|wanted"):
                list(read_frames(read, 100))

    def test_overflowing_frame_raises(self):
        payload = encode_slab_chunk(_morton_points(60, seed=32))
        read = self._stream([frame_chunk(60, payload)])
        with pytest.raises(WireError, match="bad slab frame"):
            list(read_frames(read, 40))

    def test_zero_row_frame_raises(self):
        read = self._stream([struct.pack("<II", 0, 0)])
        with pytest.raises(WireError, match="bad slab frame"):
            list(read_frames(read, 10))


# ----------------------------------------------------------- negotiation


class TestNegotiation:
    def test_caps_tables(self):
        assert wire_caps() == {"candidates": ["q16", "f32"],
                               "slab_rows": ["d16", "f32"]}
        assert wire_caps("f32") == {"candidates": ["f32"],
                                    "slab_rows": ["f32"]}

    def test_negotiate_matrix(self):
        full = wire_caps()
        assert negotiate("auto", full, "candidates") == "q16"
        assert negotiate("auto", full, "slab_rows") == "d16"
        assert negotiate("q16", full, "candidates") == "q16"
        # mismatches all fall back, never raise
        assert negotiate("f32", full, "candidates") == "f32"
        assert negotiate("auto", None, "candidates") == "f32"
        assert negotiate("auto", {}, "slab_rows") == "f32"
        assert negotiate("auto", wire_caps("f32"), "candidates") == "f32"
        assert negotiate("q16", full, "slab_rows") == "f32"

    def test_negotiator_table(self):
        neg = WireNegotiator("auto")
        neg.set_caps("http://a:1/", wire_caps())
        neg.set_caps("http://b:2", None)  # old binary
        assert neg.codec_for("http://a:1") == "q16"
        assert neg.codec_for("http://a:1/", "slab_rows") == "d16"
        assert neg.codec_for("http://b:2") == "f32"
        assert neg.codec_for("http://never-seen:9") == "f32"
        snap = neg.snapshot()
        assert snap["mode"] == "auto"
        assert snap["negotiated"]["http://b:2"]["candidates"] == "f32"

    def test_negotiator_rejects_bad_mode(self):
        with pytest.raises(ValueError, match="wire mode"):
            WireNegotiator("brotli")

    def test_wire_stats_accounting(self):
        st = WireStats()
        st.add("candidates", "q16", 100, 10)
        st.add("candidates", "q16", 50, 10)
        st.add("slab_rows", "d16", 999)
        snap = st.snapshot()
        assert snap["candidates"]["q16"] == {
            "bytes": 150, "rows": 20, "bytes_per_row": 7.5}
        assert "bytes_per_row" not in snap["slab_rows"]["d16"]
        lines = st.prometheus_lines()
        assert ('knn_wire_bytes_total{path="candidates",codec="q16"} 150'
                in lines)
        assert ('knn_wire_bytes_per_row{path="candidates",codec="q16"} '
                '7.5' in lines)


# --------------------------------------------------- HTTP host integration


def _boot(engine, **kw):
    from mpi_cuda_largescaleknn_tpu.serve.frontend import HostSliceServer

    srv = HostSliceServer(("127.0.0.1", 0), engine, routing="bounds",
                          **kw)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    srv.ready = True
    return srv, f"http://127.0.0.1:{srv.server_address[1]}"


@pytest.fixture(scope="module")
def wire_hosts():
    """ONE small candidate-emitting slab engine behind two servers: a
    ``wire="auto"`` host and a ``wire="f32"`` host (the supported way to
    emulate an old binary) — same engine, so every difference on the
    wire is the codec's doing."""
    from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
    from mpi_cuda_largescaleknn_tpu.serve.engine import ResidentKnnEngine

    pts = _morton_points(256, seed=40)
    eng = ResidentKnnEngine(pts, K, mesh=get_mesh(2), engine="tiled",
                            bucket_size=32, max_batch=32, min_batch=8,
                            emit="candidates")
    eng.warmup()
    auto_srv, auto_url = _boot(eng)
    f32_srv, f32_url = _boot(eng, wire="f32")
    yield pts, auto_url, f32_url
    auto_srv.close()
    f32_srv.close()


def _post_route(url, q, wire=None):
    qs = f"?wire={wire}" if wire else ""
    req = urllib.request.Request(
        url + "/route_knn" + qs, data=np.ascontiguousarray(q, "<f4")
        .tobytes(), headers={"Content-Type": "application/octet-stream"})
    with urllib.request.urlopen(req, timeout=60) as r:
        return r.headers.get("X-Knn-Wire"), r.read()


def _queries(pts, m=12, seed=50):
    rng = np.random.default_rng(seed)
    return (pts[rng.integers(0, len(pts), m)]
            + rng.normal(scale=1e-3, size=(m, 3)).astype(np.float32))


class TestHostWireHttp:
    def test_stats_advertise_caps_at_root(self, wire_hosts):
        _pts, auto_url, f32_url = wire_hosts
        for url, mode in ((auto_url, "auto"), (f32_url, "f32")):
            with urllib.request.urlopen(url + "/stats", timeout=30) as r:
                stats = json.loads(r.read())
            assert stats["wire"] == wire_caps(mode)
            # deliberately OUTSIDE the engine sub-dict: replica
            # fingerprints must not move when a codec is added
            assert "wire" not in stats.get("engine", {})

    def test_legacy_request_gets_precodec_body(self, wire_hosts):
        pts, auto_url, f32_url = wire_hosts
        q = _queries(pts)
        wire, body = _post_route(auto_url, q)
        assert wire is None
        assert len(body) == 8 * len(q) * K
        # and the f32-only host serves the very same bytes
        wire2, body2 = _post_route(f32_url, q)
        assert wire2 is None and body2 == body

    def test_q16_brackets_the_f32_answer(self, wire_hosts):
        pts, auto_url, _ = wire_hosts
        q = _queries(pts)
        m = len(q)
        _, f32_body = _post_route(auto_url, q)
        d2 = np.frombuffer(f32_body, "<f4", count=m * K).reshape(m, K)
        idx = np.frombuffer(f32_body, "<i4", count=m * K,
                            offset=4 * m * K).reshape(m, K)
        wire, body = _post_route(auto_url, q, wire="q16")
        assert wire == "q16"
        assert len(body) < len(f32_body)
        hi, lo, got_idx = decode_candidates_q16(body, m, K)
        assert np.array_equal(got_idx, idx)
        valid = idx >= 0
        assert (hi[valid] >= d2[valid]).all()
        assert (lo[valid] <= d2[valid]).all()

    def test_q16_ask_to_f32_host_is_a_clean_fallback(self, wire_hosts):
        """The codec-mismatch bar: an f32-only host answers a ?wire=q16
        ask with the plain f32 body and no codec header — the response
        header selects the parse, so the caller never hits a decode
        error, it just reads uncompressed rows."""
        pts, auto_url, f32_url = wire_hosts
        q = _queries(pts)
        wire, body = _post_route(f32_url, q, wire="q16")
        assert wire is None
        assert len(body) == 8 * len(q) * K
        _, ref = _post_route(auto_url, q)
        assert body == ref

    def test_x32_refetch_carries_exact_d2(self, wire_hosts):
        pts, auto_url, _ = wire_hosts
        q = _queries(pts)
        m = len(q)
        _, f32_body = _post_route(auto_url, q)
        wire, body = _post_route(auto_url, q, wire="x32")
        assert wire == "x32"
        assert len(body) == 4 * m * K
        assert body == f32_body[:4 * m * K]

    def test_slab_pull_codecs_are_lossless(self, wire_hosts):
        from mpi_cuda_largescaleknn_tpu.serve.replica import pull_slab_rows

        pts, auto_url, f32_url = wire_hosts
        for wire in ("d16", "f32", "none"):  # "none" = legacy single-shot
            rows, off = pull_slab_rows(auto_url, wire=wire)
            assert off == 0
            assert np.array_equal(rows.view(np.uint32),
                                  pts.view(np.uint32)), wire
        # an f32-mode host streams chunked f32 — still lossless
        rows, _ = pull_slab_rows(f32_url, wire="d16")
        assert np.array_equal(rows.view(np.uint32), pts.view(np.uint32))

    def test_slab_pull_subrange(self, wire_hosts):
        from mpi_cuda_largescaleknn_tpu.serve.replica import pull_slab_rows

        pts, auto_url, _ = wire_hosts
        rows, off = pull_slab_rows(auto_url, begin=17, end=101)
        assert off == 17
        assert np.array_equal(rows.view(np.uint32),
                              pts[17:101].view(np.uint32))


# ------------------------------------------- skip-cold drift admission


class _FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


@pytest.fixture()
def drift_stream():
    from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
    from mpi_cuda_largescaleknn_tpu.serve.slabpool import (
        StreamingKnnEngine,
    )

    clock = _FakeClock()
    stream = StreamingKnnEngine(points=_morton_points(128, seed=60),
                                num_slabs=2, k=2, mesh=get_mesh(2),
                                engine="tiled", bucket_size=16,
                                max_batch=16, min_batch=4,
                                clock=clock)
    yield stream, clock
    stream.close()


class TestSkipColdDriftAdmission:
    def _stall(self, stream, seconds):
        """Pin the pool's cumulative stall clock to a chosen value."""
        stream._pool.stall_totals = (
            lambda tenant=None: (1, float(seconds)))

    def test_healthy_pool_admits(self, drift_stream):
        stream, clock = drift_stream
        self._stall(stream, 0.0)
        for _ in range(5):
            clock.t += 1.0
            assert stream._skip_cold_admit()
        assert stream.skip_cold_refusals == 0

    def test_stalling_pool_refuses_then_readmits(self, drift_stream):
        stream, clock = drift_stream
        # 10s of wall, no stalls: healthy baseline in the ring
        self._stall(stream, 0.0)
        for _ in range(10):
            clock.t += 1.0
            assert stream._skip_cold_admit()
        # now every wall second is ~50% stall — far above the 0.25 limit
        stall = 0.0
        refused = 0
        for _ in range(30):
            clock.t += 1.0
            stall += 0.5
            self._stall(stream, stall)
            if not stream._skip_cold_admit():
                refused += 1
        assert refused > 0
        assert stream.skip_cold_refusals == refused
        # the stalls stop; once the window drains the tier re-opens
        self._stall(stream, stall)
        admitted = False
        for _ in range(2 * stream.skip_cold_window):
            clock.t += 1.0
            if stream._skip_cold_admit():
                admitted = True
                break
        assert admitted, "admission never recovered after stalls ceased"

    def test_refused_plan_serves_exact(self, drift_stream):
        from mpi_cuda_largescaleknn_tpu.serve.recall import RecallPlan

        stream, clock = drift_stream
        q = _morton_points(8, seed=61)
        exact = [np.asarray(x) for x in stream.query(q)]
        # poison the window: 100% stall fraction
        stall = 0.0
        for _ in range(10):
            clock.t += 1.0
            stall += 1.0
            self._stall(stream, stall)
            stream._skip_cold_admit()
        before = stream.skip_cold_refusals
        assert before > 0
        plan = RecallPlan(name="drifty", stream_skip_cold=True,
                          recall_estimated=0.9)
        d, i = stream.query(q, plan=plan)
        # the plan was refused (counted) and the batch served exact
        assert stream.skip_cold_refusals > before
        assert np.array_equal(np.asarray(d), exact[0])
        assert np.array_equal(np.asarray(i), exact[1])
        assert stream.stats()["streaming"]["skip_cold_refusals"] \
            == stream.skip_cold_refusals

    def test_stats_surface_the_knobs(self, drift_stream):
        stream, _clock = drift_stream
        s = stream.stats()["streaming"]
        assert s["skip_cold_stall_limit"] == pytest.approx(0.25)
        assert "skip_cold_refusals" in s
