"""Multi-host execution: 2 cooperating CPU processes == 1 process, byte-for-byte.

The TPU-world "pod without a pod" fixture: two OS processes, one CPU device
each, joined into a single 2-device mesh by ``jax.distributed.initialize``
(gloo collectives). The multi-host CLI path (cli/multihost.py) must produce
the SAME bytes as the single-process CLI at the same shard count — the
reference's rank-count-invariance oracle (SURVEY.md §4) applied across
process boundaries.

These tests spawn their own subprocesses with a clean CPU env (the outer
pytest process stays off the TPU tunnel, tests/conftest.py).
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _cpu_env(n_local_devices: int = 1) -> dict:
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
    if n_local_devices > 1:
        flags.append(
            f"--xla_force_host_platform_device_count={n_local_devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    return env


@pytest.mark.parametrize("n,k", [(600, 5)])
def test_two_process_matches_single_process(tmp_path, n, k):
    rng = np.random.default_rng(3)
    pts = rng.random((n, 3)).astype(np.float32)
    in_path = str(tmp_path / "pts.float3")
    pts.tofile(in_path)

    # single process, 2 virtual devices -> reference output at R=2
    single_out = str(tmp_path / "single.float")
    r = subprocess.run(
        [sys.executable, "-m", "mpi_cuda_largescaleknn_tpu.cli.unordered_main",
         in_path, "-o", single_out, "-k", str(k), "--shards", "2",
         "--bucket-size", "64"],
        env=_cpu_env(2), capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]

    # two processes, 1 device each, same R=2 mesh spanning both
    multi_out = str(tmp_path / "multi.float")
    port = _free_port()
    base = [sys.executable, "-m",
            "mpi_cuda_largescaleknn_tpu.cli.unordered_main",
            in_path, "-o", multi_out, "-k", str(k), "--bucket-size", "64",
            "--coordinator", f"127.0.0.1:{port}", "--num-hosts", "2"]
    p1 = subprocess.Popen(base + ["--host-id", "1"], env=_cpu_env(),
                          stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                          text=True)
    p0 = subprocess.Popen(base + ["--host-id", "0"], env=_cpu_env(),
                          stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                          text=True)
    out0, err0 = p0.communicate(timeout=600)
    out1, err1 = p1.communicate(timeout=600)
    assert p0.returncode == 0, err0[-2000:]
    assert p1.returncode == 0, err1[-2000:]

    want = np.fromfile(single_out, np.float32)
    got = np.fromfile(multi_out, np.float32)
    assert want.shape == got.shape == (n,)
    np.testing.assert_array_equal(got, want)


def test_two_process_prepartitioned_matches_single(tmp_path):
    rng = np.random.default_rng(11)
    n, k = 500, 4
    pts = rng.random((n, 3)).astype(np.float32)
    pts = pts[np.argsort(pts[:, 0], kind="stable")]
    parts = [pts[:n // 2], pts[n // 2:]]
    names = []
    for i, p in enumerate(parts):
        f = str(tmp_path / f"part{i}.float3")
        p.tofile(f)
        names.append(f)
    flist = str(tmp_path / "files.txt")
    with open(flist, "w") as f:
        f.write("\n".join(names) + "\n")

    r = subprocess.run(
        [sys.executable, "-m",
         "mpi_cuda_largescaleknn_tpu.cli.prepartitioned_main",
         flist, "-o", str(tmp_path / "single"), "-k", str(k),
         "--bucket-size", "64"],
        env=_cpu_env(2), capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]

    port = _free_port()
    base = [sys.executable, "-m",
            "mpi_cuda_largescaleknn_tpu.cli.prepartitioned_main",
            flist, "-o", str(tmp_path / "multi"), "-k", str(k),
            "--bucket-size", "64",
            "--coordinator", f"127.0.0.1:{port}", "--num-hosts", "2"]
    p1 = subprocess.Popen(base + ["--host-id", "1"], env=_cpu_env(),
                          stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                          text=True)
    p0 = subprocess.Popen(base + ["--host-id", "0"], env=_cpu_env(),
                          stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                          text=True)
    _, err0 = p0.communicate(timeout=600)
    _, err1 = p1.communicate(timeout=600)
    assert p0.returncode == 0, err0[-2000:]
    assert p1.returncode == 0, err1[-2000:]

    for i in range(2):
        want = np.fromfile(str(tmp_path / f"single_{i:06d}.float"),
                           np.float32)
        got = np.fromfile(str(tmp_path / f"multi_{i:06d}.float"), np.float32)
        np.testing.assert_array_equal(got, want)


def test_multihost_presize_clears_stale_bytes(tmp_path):
    """A stale longer output file from a prior run must not leak trailing
    bytes into the new output (io/native_io.cpp lsk_create_sized)."""
    rng = np.random.default_rng(5)
    n, k = 300, 4
    pts = rng.random((n, 3)).astype(np.float32)
    in_path = str(tmp_path / "pts.float3")
    pts.tofile(in_path)
    out_path = str(tmp_path / "out.float")
    np.full(4 * n, 7.0, np.float32).tofile(out_path)  # stale, 4x longer

    port = _free_port()
    base = [sys.executable, "-m",
            "mpi_cuda_largescaleknn_tpu.cli.unordered_main",
            in_path, "-o", out_path, "-k", str(k), "--bucket-size", "64",
            "--coordinator", f"127.0.0.1:{port}", "--num-hosts", "2"]
    p1 = subprocess.Popen(base + ["--host-id", "1"], env=_cpu_env(),
                          stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                          text=True)
    p0 = subprocess.Popen(base + ["--host-id", "0"], env=_cpu_env(),
                          stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                          text=True)
    _, err0 = p0.communicate(timeout=600)
    _, err1 = p1.communicate(timeout=600)
    assert p0.returncode == 0, err0[-2000:]
    assert p1.returncode == 0, err1[-2000:]

    got = np.fromfile(out_path, np.float32)
    assert got.shape == (n,), "stale trailing bytes survived the rewrite"
    assert np.all(np.isfinite(got)) and not np.any(got == 7.0)


def test_two_process_chunked_device_merge_matches_single(tmp_path):
    """The lifted multi-host ``merge=device`` chunked path: the chunk is
    staged sharded (each host uploads its own rows), the program
    all_gathers it, and ``device_merge_final`` reduces on the GLOBAL
    2-process mesh — byte-identical to the single-process run of the same
    config (which runs the literally identical SPMD program)."""
    rng = np.random.default_rng(29)
    n, k = 600, 5
    pts = rng.random((n, 3)).astype(np.float32)
    # duplicates force cross-host equal-distance ties through the
    # global-axis reduction
    pts[n // 2:] = pts[: n - n // 2]
    in_path = str(tmp_path / "pts.float3")
    pts.tofile(in_path)
    chunk = ["--query-chunk", "100", "--bucket-size", "64",
             "--merge", "device"]

    single_out = str(tmp_path / "single.float")
    r = subprocess.run(
        [sys.executable, "-m",
         "mpi_cuda_largescaleknn_tpu.cli.unordered_main",
         in_path, "-o", single_out, "-k", str(k), "--shards", "2"] + chunk,
        env=_cpu_env(2), capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]

    multi_out = str(tmp_path / "multi.float")
    port = _free_port()
    base = [sys.executable, "-m",
            "mpi_cuda_largescaleknn_tpu.cli.unordered_main",
            in_path, "-o", multi_out, "-k", str(k),
            "--coordinator", f"127.0.0.1:{port}", "--num-hosts", "2"] + chunk
    p1 = subprocess.Popen(base + ["--host-id", "1"], env=_cpu_env(),
                          stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                          text=True)
    p0 = subprocess.Popen(base + ["--host-id", "0"], env=_cpu_env(),
                          stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                          text=True)
    _, err0 = p0.communicate(timeout=600)
    _, err1 = p1.communicate(timeout=600)
    assert p0.returncode == 0, err0[-2000:]
    assert p1.returncode == 0, err1[-2000:]

    want = np.fromfile(single_out, np.float32)
    got = np.fromfile(multi_out, np.float32)
    assert want.shape == got.shape == (n,)
    np.testing.assert_array_equal(got, want)


def test_two_process_query_chunk_matches_single(tmp_path):
    """--query-chunk (and --checkpoint-dir) composed with multi-host: two
    processes, >=3 chunks per shard, byte-identical to the single-process
    run of the same config (VERDICT r3 item 8 — the gate to the 10B/k=100
    stretch regime)."""
    rng = np.random.default_rng(17)
    n, k = 600, 5
    pts = rng.random((n, 3)).astype(np.float32)
    in_path = str(tmp_path / "pts.float3")
    pts.tofile(in_path)
    # npad = 300 per shard; chunk 100 -> 3 chunks per shard
    chunk = ["--query-chunk", "100", "--bucket-size", "64"]

    single_out = str(tmp_path / "single.float")
    r = subprocess.run(
        [sys.executable, "-m",
         "mpi_cuda_largescaleknn_tpu.cli.unordered_main",
         in_path, "-o", single_out, "-k", str(k), "--shards", "2"] + chunk,
        env=_cpu_env(2), capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]

    multi_out = str(tmp_path / "multi.float")
    port = _free_port()
    base = [sys.executable, "-m",
            "mpi_cuda_largescaleknn_tpu.cli.unordered_main",
            in_path, "-o", multi_out, "-k", str(k),
            "--coordinator", f"127.0.0.1:{port}", "--num-hosts", "2",
            "--checkpoint-dir", str(tmp_path / "ck")] + chunk
    p1 = subprocess.Popen(base + ["--host-id", "1"], env=_cpu_env(),
                          stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                          text=True)
    p0 = subprocess.Popen(base + ["--host-id", "0"], env=_cpu_env(),
                          stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                          text=True)
    _, err0 = p0.communicate(timeout=600)
    _, err1 = p1.communicate(timeout=600)
    assert p0.returncode == 0, err0[-2000:]
    assert p1.returncode == 0, err1[-2000:]

    want = np.fromfile(single_out, np.float32)
    got = np.fromfile(multi_out, np.float32)
    assert want.shape == got.shape == (n,)
    np.testing.assert_array_equal(got, want)
