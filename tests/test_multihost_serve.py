"""Multi-host serving: 2 pod processes + front end == 1 process, byte-for-byte.

The serving analogue of tests/test_multihost.py: two serve_main processes,
one CPU device each, joined into a single 2-device global mesh
(jax.distributed + gloo) with ``merge="device"`` — each emits its 1/R row
slices of the pod-final answer over POST /shard_knn — fronted by the
in-process ``FrontendServer``/``PodFanout``. Every served byte (distances
AND neighbor ids, ties included) must equal a single-process
ResidentKnnEngine over a same-size mesh with the same configuration: the
pod runs the SAME SPMD program, just spread over processes, with the PR-4
Morton/multi-bucket pipeline riding unchanged inside each host's program.

Duplicate-heavy query/point sets force cross-host equal-distance ties, so
any tie-discipline divergence at the pod level shows up as an id mismatch.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import threading
import urllib.request

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
K = 5


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _cpu_env() -> dict:
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count"))
    return env


def _dup_points(n, seed):
    from tests.oracle import random_points

    base = random_points(max(n // 4, 8), seed=seed)
    reps = -(-n // len(base))
    return np.tile(base, (reps, 1))[:n].copy()


@pytest.fixture(scope="module")
def pod(tmp_path_factory):
    """Two serve_main pod processes over one 2-device mesh + their URLs."""
    tmp = tmp_path_factory.mktemp("pod")
    points = _dup_points(600, seed=23)
    in_path = str(tmp / "pts.float3")
    points.tofile(in_path)

    coord = _free_port()
    ports = [_free_port(), _free_port()]
    base = [sys.executable, "-m",
            "mpi_cuda_largescaleknn_tpu.cli.serve_main",
            in_path, "-k", str(K), "--engine", "tiled",
            "--bucket-size", "64", "--max-batch", "32", "--min-batch", "16",
            "--merge", "device",
            "--coordinator", f"127.0.0.1:{coord}", "--num-hosts", "2"]
    procs = [subprocess.Popen(
        base + ["--host-id", str(i), "--port", str(ports[i])],
        env=_cpu_env(), stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True) for i in (1, 0)]
    urls = [f"http://127.0.0.1:{p}" for p in ports]
    try:
        from mpi_cuda_largescaleknn_tpu.serve.frontend import wait_hosts_ready

        try:
            wait_hosts_ready(urls, timeout_s=300.0)
        except TimeoutError:
            outs = [p.communicate(timeout=5) if p.poll() is not None
                    else ("", "<still running>") for p in procs]
            raise AssertionError(f"pod never came up: {outs}")
        yield urls, points
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()


@pytest.fixture(scope="module")
def reference_engine():
    """Single-process twin of the pod: same mesh size, same config."""
    from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
    from mpi_cuda_largescaleknn_tpu.serve.engine import ResidentKnnEngine

    points = _dup_points(600, seed=23)
    eng = ResidentKnnEngine(points, K, mesh=get_mesh(2), engine="tiled",
                            bucket_size=64, max_batch=32, min_batch=16,
                            merge="device")
    eng.warmup()
    return eng


@pytest.fixture(scope="module")
def frontend(pod):
    from mpi_cuda_largescaleknn_tpu.serve.frontend import build_frontend

    urls, _points = pod
    srv = build_frontend(urls, port=0, pipeline_depth=2)
    srv.ready = True
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield srv
    srv.close()


def _post_knn(url, q, timeout=120):
    req = urllib.request.Request(
        url + "/knn",
        data=json.dumps({"queries": q.tolist(),
                         "neighbors": True}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


class TestPodServedByteIdentical:
    def test_ragged_batches_match_single_process(self, frontend, pod,
                                                 reference_engine):
        """The acceptance bar: every served batch — ragged sizes padding
        to both shape buckets, queries ON duplicated points for
        distance-0 cross-host ties — is byte-identical to the
        single-process engine at merge=device."""
        _urls, points = pod
        base = f"http://127.0.0.1:{frontend.server_address[1]}"
        from tests.oracle import kth_nn_dist, random_points

        for n in (1, 5, 16, 17, 32):
            q = random_points(n, seed=300 + n)
            q[: n // 2] = points[: n // 2]
            resp = _post_knn(base, q)
            want_d, want_n = reference_engine.query(q)
            got_d = np.asarray(resp["dists"], np.float32)
            got_n = np.asarray(resp["neighbors"], np.int32)
            np.testing.assert_array_equal(got_d, want_d)
            np.testing.assert_array_equal(got_n, want_n)
            # and both are the true k-NN against numpy
            np.testing.assert_allclose(got_d, kth_nn_dist(q, points, K),
                                       rtol=5e-7, atol=1e-37)

    def test_concurrent_clients_through_pipelined_fanout(self, frontend,
                                                         reference_engine):
        """Concurrent requests coalesce into pod batches under pipeline
        depth 2; demuxed per-request answers still match the reference."""
        from tests.oracle import random_points

        base = f"http://127.0.0.1:{frontend.server_address[1]}"
        results = {}

        def client(i):
            q = random_points(3 + 2 * i, seed=600 + i)
            results[i] = (q, _post_knn(base, q))

        ths = [threading.Thread(target=client, args=(i,)) for i in range(6)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        assert len(results) == 6
        for q, resp in results.values():
            want_d, want_n = reference_engine.query(q)
            np.testing.assert_array_equal(
                np.asarray(resp["dists"], np.float32), want_d)
            np.testing.assert_array_equal(
                np.asarray(resp["neighbors"], np.int32), want_n)

    def test_health_stats_and_straggler_metrics(self, frontend, pod):
        """/healthz aggregates per-host health; /stats and /metrics carry
        the fan-out's per-host latency + straggler accounting and the
        stall-aware batcher's dispatch-stall counter."""
        urls, _ = pod
        base = f"http://127.0.0.1:{frontend.server_address[1]}"
        with urllib.request.urlopen(base + "/healthz", timeout=30) as r:
            health = json.loads(r.read())
        assert r.status == 200 and health["status"] == "ok"
        assert set(health["hosts"]) == set(urls)
        assert all(h["ok"] for h in health["hosts"].values())

        with urllib.request.urlopen(base + "/stats", timeout=30) as r:
            stats = json.loads(r.read())
        fan = stats["fanout"]
        assert fan["batches"] > 0 and fan["broken"] is None
        assert set(fan["per_host"]) == set(urls)
        for h in fan["per_host"].values():
            assert h["ok"] > 0 and h["errors"] == 0
        # each host fetched only its slices: pod fetch bytes ≈ one final
        # result, and every host's engine reports multihost mode
        for url in urls:
            e = stats["hosts"][url]["engine"]
            assert e["multihost"] is True and e["merge"] == "device"
            assert e["fetch_bytes"] > 0

        m = urllib.request.urlopen(base + "/metrics",
                                   timeout=30).read().decode()
        assert "knn_fanout_straggler_seconds_total" in m
        assert "knn_dispatch_stall_seconds_total" in m
        for url in urls:
            assert f'knn_host_up{{host="{url}"}} 1' in m

    def test_pod_fetch_bytes_are_one_result_per_batch(self, frontend, pod):
        """The headline claim: summed across hosts, fetched result bytes
        per padded batch equal ONE [qpad] + [qpad, k] result — an
        every-host-fetches-everything design would pay hosts x that."""
        urls, _ = pod
        from tests.oracle import random_points

        base = f"http://127.0.0.1:{frontend.server_address[1]}"

        def pod_fetch_bytes():
            total = 0
            for url in urls:
                with urllib.request.urlopen(url + "/stats", timeout=30) as r:
                    total += json.loads(r.read())["engine"]["fetch_bytes"]
            return total

        before = pod_fetch_bytes()
        _post_knn(base, random_points(16, seed=9))  # pads to qpad=16
        after = pod_fetch_bytes()
        qpad = 16
        assert after - before == qpad * 4 + qpad * K * 4
