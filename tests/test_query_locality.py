"""Query-locality pipeline: Morton-sorted admission + multi-bucket serving
traversal must be BIT-IDENTICAL to the single-bucket unsorted path.

The exactness contract (ISSUE 4): for the same request stream, an engine
with ``query_buckets=1`` (no admission sort, one whole-batch query bucket —
the pre-locality serving path) and an engine with ``query_buckets>1``
(Morton sort + per-slice AABBs) return the same bytes after demux —
distances AND equal-distance tie order — across shard counts, both merge
placements, duplicate-heavy point sets, and ragged (padded) batch sizes.
The mechanism is the canonical (dist2, id) tie discipline in
``merge_candidates(canonical=True)`` plus the non-strict visit predicate
(ops/tiled.py), which make the traversal's output independent of the visit
schedule; the admission sort then demuxes through its inverse permutation.

Also here: the tile-skip counters (executed + skipped == the static
schedule ceiling; clustered batches skip more at B>1) and the AOT
compile-count discipline with the query_buckets key component.
"""

import numpy as np
import pytest

from mpi_cuda_largescaleknn_tpu.serve.engine import ResidentKnnEngine
from tests.oracle import assert_dist_equal, kth_nn_dist, random_points

K = 4


def _dup_points(n, seed):
    """Duplicate-heavy point set: every base point appears ~4x, spread
    across slab shards AND across spatial buckets within a shard, so
    equal-distance candidates with different global ids exist for nearly
    every query — the tie cases the canonical order must pin down."""
    base = random_points(max(n // 4, 8), seed=seed)
    reps = -(-n // len(base))
    return np.tile(base, (reps, 1))[:n].copy()


def _mesh(r):
    from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh

    return get_mesh(r)


def _engine(points, r, qb, merge="host", **kw):
    args = dict(engine="tiled", bucket_size=32, max_batch=32, min_batch=16)
    args.update(kw)
    return ResidentKnnEngine(points, K, mesh=_mesh(r), merge=merge,
                             query_buckets=qb, **args)


class TestMultiBucketBitIdentical:
    @pytest.mark.parametrize("r", [1, 2, 4])
    @pytest.mark.parametrize("merge", ["host", "device"])
    def test_sorted_multibucket_equals_unsorted_b1(self, r, merge):
        """The acceptance bar: B=auto + Morton admission == B=1 unsorted,
        bit for bit, at R in {1, 2, 4} under both merge placements, with
        duplicate points forcing distance ties and ragged sizes forcing
        sentinel padding."""
        points = _dup_points(600, seed=r)
        base = _engine(points, r, qb=1, merge=merge)
        multi = _engine(points, r, qb=0, merge=merge)
        assert not base.sort_queries and multi.sort_queries
        assert any(b > 1 for b in multi.query_buckets.values())
        for n in (1, 5, 16, 17, 29, 32):  # ragged sizes pad up to 16/32
            q = random_points(n, seed=100 * r + n)
            q[: n // 2] = points[: n // 2]  # query ON duplicated points:
            db, nb = base.query(q)         # distance-0 ties included
            dm, nm = multi.query(q)
            np.testing.assert_array_equal(db, dm)
            np.testing.assert_array_equal(nb, nm)
            assert_dist_equal(dm, kth_nn_dist(q, points, K))

    def test_explicit_query_buckets_equal_too(self):
        """Any B produces the same bytes — not just auto: the canonical
        tie order is bucket-geometry independent."""
        points = _dup_points(500, seed=9)
        engines = [_engine(points, 4, qb) for qb in (1, 2, 4, 0)]
        q = random_points(32, seed=5)
        q[:16] = points[:16]
        want = engines[0].query(q)
        for eng in engines[1:]:
            got = eng.query(q)
            np.testing.assert_array_equal(want[0], got[0])
            np.testing.assert_array_equal(want[1], got[1])

    def test_scattered_then_identical_rows_demux(self):
        """Rows that are permutations of each other demux identically:
        the same queries in two different request orders return
        row-aligned identical answers (the inverse-permutation scatter)."""
        points = random_points(400, seed=3)
        eng = _engine(points, 4, qb=0)
        q = random_points(24, seed=8)
        perm = np.random.default_rng(0).permutation(len(q))
        d1, n1 = eng.query(q)
        d2, n2 = eng.query(q[perm])
        np.testing.assert_array_equal(d1[perm], d2)
        np.testing.assert_array_equal(n1[perm], n2)

    def test_max_radius_underfull_rows_match(self):
        """Under-full heaps (max_radius cutoff): the untouched r^2 / -1
        slots must stay bit-identical across bucketings."""
        points = random_points(400, seed=3)
        base = _engine(points, 4, qb=1, max_radius=0.05)
        multi = _engine(points, 4, qb=4, max_radius=0.05)
        q = random_points(24, seed=7)
        db, nb = base.query(q)
        dm, nm = multi.query(q)
        np.testing.assert_array_equal(db, dm)
        np.testing.assert_array_equal(nb, nm)


class TestTileAccounting:
    def test_executed_plus_skipped_is_the_schedule(self):
        """Per batch, executed + skipped tile-rows == the program's static
        ceiling (num_shards * qpad * schedule slots) — the counters are an
        exact partition of the schedule, not estimates."""
        from mpi_cuda_largescaleknn_tpu.ops.tiled import tile_schedule_slots

        points = random_points(600, seed=1)
        eng = _engine(points, 2, qb=0)
        q = random_points(20, seed=2)
        eng.query(q)
        s = eng.stats()
        qpad = eng.bucket_for(20)
        num_pb = eng._buckets.ids.shape[0] // eng.num_shards
        ceiling = eng.num_shards * qpad * tile_schedule_slots(num_pb)
        assert s["tiles_executed"] + s["tiles_skipped"] == ceiling
        assert s["tiles_executed"] > 0

    def test_blob_mixture_batch_skips_vs_b1(self):
        """The locality claim at engine granularity, deterministically: a
        batch MIXING several tight blobs (what the batcher coalesces from
        per-user requests) executes far fewer tiles on the multi-bucket
        engine — the Morton sort separates the blobs into buckets with
        tiny radii — while the B=1 engine's single AABB spans all blobs
        and degenerates toward the scattered case (same seeds, counters
        only, no timing)."""
        rng = np.random.default_rng(0)
        points = rng.random((4096, 3)).astype(np.float32)
        multi = _engine(points, 1, qb=0, bucket_size=64, max_batch=128,
                        min_batch=16)
        b1 = _engine(points, 1, qb=1, bucket_size=64, max_batch=128,
                     min_batch=16)
        centers = rng.random((8, 3))
        mixture = np.clip(
            centers[np.arange(128) % 8] + rng.normal(0, 0.02, (128, 3)),
            0, 1).astype(np.float32)
        scattered = rng.random((128, 3)).astype(np.float32)

        def tiles_for(eng, q):
            before = eng.timers.counter("tiles_executed")
            eng.query(q)
            return eng.timers.counter("tiles_executed") - before

        mc, ms = tiles_for(multi, mixture), tiles_for(multi, scattered)
        bc, bs = tiles_for(b1, mixture), tiles_for(b1, scattered)
        assert mc < ms, (mc, ms)
        assert 2 * mc <= bc, (mc, bc)  # the bench's <= 0.5x claim
        assert ms <= bs, (ms, bs)

    def test_flat_engine_counts_nothing(self):
        points = random_points(200, seed=4)
        eng = ResidentKnnEngine(points, K, mesh=_mesh(2),
                                engine="bruteforce", max_batch=16,
                                min_batch=16)
        eng.query(random_points(8, seed=1))
        s = eng.stats()
        assert s["tiles_executed"] == 0 and s["tiles_skipped"] == 0
        assert s["query_buckets"] == {"16": 1}


class TestCompileDiscipline:
    def test_warmup_compiles_one_program_per_bucket(self):
        """query_buckets resolves per qpad INSIDE the AOT key, so warmup
        still compiles exactly len(shape_buckets) programs and ragged
        traffic adds zero — the recompile-freedom contract."""
        points = random_points(500, seed=6)
        eng = _engine(points, 4, qb=0, max_batch=64)
        info = eng.warmup()
        assert eng.compile_count == len(eng.shape_buckets)
        assert set(info["per_bucket_s"]) == set(eng.shape_buckets)
        assert info["query_buckets"] == dict(eng.query_buckets)
        # all-pad warmup traversals prune everything: honest first counters
        assert info["tiles_executed"] == 0
        assert info["tiles_skipped"] > 0
        for n in (1, 3, 16, 17, 31, 64):
            eng.query(random_points(n, seed=n))
        assert eng.compile_count == len(eng.shape_buckets)

    def test_resolver_properties(self):
        from mpi_cuda_largescaleknn_tpu.parallel.ring import (
            resolve_query_buckets,
        )

        for qpad in (8, 16, 32, 64, 128, 1024):
            for k in (1, 4, 16, 100):
                for setting in (0, 1, 3, 8, 1 << 20):
                    b = resolve_query_buckets(setting, qpad, k)
                    assert qpad % b == 0, (qpad, k, setting, b)
                    assert qpad // b >= 8 or b == 1
        assert resolve_query_buckets(1, 128, 16) == 1     # explicit off
        assert resolve_query_buckets(3, 128, 16) == 4     # rounds to pow2
        assert resolve_query_buckets(0, 8, 16) == 1       # tiny batch
        assert resolve_query_buckets(0, 128, 16) == 8     # ~k per bucket


class TestServedEndToEnd:
    def test_concurrent_clients_through_sorted_server(self):
        """Full stack at query_buckets=auto, pipeline depth 2: concurrent
        clients' rows come back in caller order (inverse-permutation demux
        crosses the batcher's coalescing) and oracle-exact."""
        import json
        import threading
        import urllib.request

        from mpi_cuda_largescaleknn_tpu.serve.server import build_server

        points = _dup_points(800, seed=11)
        eng = _engine(points, 4, qb=0, max_batch=128)
        eng.warmup()
        srv = build_server(eng, port=0, max_delay_s=0.002, pipeline_depth=2)
        srv.ready = True
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{srv.server_address[1]}"
        results = {}

        def client(i):
            q = random_points(5 + 3 * i, seed=300 + i)
            req = urllib.request.Request(
                base + "/knn",
                data=json.dumps({"queries": q.tolist(),
                                 "neighbors": True}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=60) as resp:
                results[i] = (q, json.loads(resp.read()))

        try:
            ths = [threading.Thread(target=client, args=(i,))
                   for i in range(6)]
            for t in ths:
                t.start()
            for t in ths:
                t.join()
            assert len(results) == 6
            for q, resp in results.values():
                assert_dist_equal(np.asarray(resp["dists"], np.float32),
                                  kth_nn_dist(q, points, K))
            m = urllib.request.urlopen(base + "/metrics",
                                       timeout=10).read().decode()
            assert "# TYPE knn_tiles_executed_total counter" in m
            assert "# TYPE knn_tiles_skipped_total counter" in m
            assert 'knn_query_buckets{qpad="128"}' in m
        finally:
            srv.close()
