"""utils/compile_cache.py — env bootstrap semantics.

The helper must (a) default the cache dir to a repo-local path and create
it, (b) never override an operator-exported value (chip_session.sh sets
its own), and (c) stay idempotent so bench.py's parent + child and the
standalone tools can all call it. Pure env manipulation — no jax import,
so these run instantly on the CPU fixture.
"""

import os

from mpi_cuda_largescaleknn_tpu.utils.compile_cache import (
    _REPO_ROOT, enable_persistent_cache)

_VARS = ("JAX_COMPILATION_CACHE_DIR",
         "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
         "JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES")


def _clear(monkeypatch):
    # setitem-then-delitem (not bare delenv): setitem records the var's
    # ORIGINAL state — including absence — so values the code under test
    # writes into os.environ are rolled back at teardown instead of
    # leaking a deleted tmp cache dir into later jax-importing tests
    for v in _VARS:
        monkeypatch.setitem(os.environ, v, "sentinel")
        monkeypatch.delitem(os.environ, v)


def test_defaults_to_repo_local_dir_and_creates_it(monkeypatch, tmp_path):
    _clear(monkeypatch)
    target = str(tmp_path / "cache")
    got = enable_persistent_cache(target)
    assert got == target == os.environ["JAX_COMPILATION_CACHE_DIR"]
    assert os.path.isdir(target)
    assert os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] == "1"
    assert os.environ["JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES"] == "0"


def test_operator_export_wins(monkeypatch, tmp_path):
    _clear(monkeypatch)
    theirs = str(tmp_path / "operator")
    monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", theirs)
    monkeypatch.setenv("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "7")
    got = enable_persistent_cache(str(tmp_path / "mine"))
    assert got == theirs == os.environ["JAX_COMPILATION_CACHE_DIR"]
    assert os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] == "7"


def test_idempotent_and_repo_root_resolves(monkeypatch, tmp_path):
    _clear(monkeypatch)
    target = str(tmp_path / "cache")
    assert enable_persistent_cache(target) == enable_persistent_cache(target)
    # the default path is anchored at the repo root (where bench.py lives)
    assert os.path.isfile(os.path.join(_REPO_ROOT, "bench.py"))


def test_unwritable_dir_warns_but_does_not_raise(monkeypatch, tmp_path,
                                                 capsys):
    _clear(monkeypatch)

    # forced failure, not a chmod'd dir: root (this container's uid)
    # ignores permission bits, which would leave the swallow path untested
    def _boom(*a, **kw):
        raise OSError("unwritable")

    import mpi_cuda_largescaleknn_tpu.utils.compile_cache as cc
    monkeypatch.setattr(cc.os, "makedirs", _boom)
    # helper must swallow the OSError (jax itself runs uncached) but must
    # TELL the operator: a silent cache loss repays every compile (~220s
    # on-chip) forever with no visible cause
    got = enable_persistent_cache(str(tmp_path / "cache"))
    assert got == os.environ["JAX_COMPILATION_CACHE_DIR"]
    err = capsys.readouterr().err
    assert "compile cache" in err and "not writable" in err
