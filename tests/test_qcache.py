"""Certified query cache (serve/qcache.py): exactness is the whole bar.

Every reuse tier must be invisible in the served bits: exact hits replay
byte-identical rows, in-flight dedup hands joiners the owner's bytes, and
triangle-inequality radius seeds must leave engine output BITWISE
unchanged — distances AND ids, ties included — across merge placements,
streaming budgets, and routed pods. The fixtures plant the adversarial
cases on purpose: exact-duplicate coordinates (distance-0 ties at the
seed boundary), ragged batches (pad rows carry the unseeded sentinel),
and anchors identical to their revisits (the tightest possible seed).
"""

from __future__ import annotations

import json
import threading
import urllib.request

import numpy as np
import pytest

from mpi_cuda_largescaleknn_tpu.serve.qcache import (
    _SEED_FLOOR,
    QueryCache,
    SeedPool,
    certified_seeds,
)
from tests.oracle import random_points

K = 8


def _dup_points(n=900, seed=11):
    """Point set with planted exact-duplicate coordinates: rows
    [n-5:n) are copies of rows [0:5), so true top-k sets contain
    distance-0 cross-row ties — the canonical-order fold's worst case,
    and the seed boundary's (a seed derived from one copy sits one ulp
    above a kth distance the other copy ties exactly)."""
    pts = random_points(n - 5, seed=seed)
    return np.concatenate([pts, pts[:5]]).astype(np.float32)


@pytest.fixture(scope="module")
def points():
    return _dup_points()


@pytest.fixture(scope="module", params=["host", "device"])
def merge_engine(request, points):
    from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
    from mpi_cuda_largescaleknn_tpu.serve.engine import ResidentKnnEngine

    eng = ResidentKnnEngine(points, K, mesh=get_mesh(8), engine="tiled",
                            bucket_size=32, max_batch=64, min_batch=8,
                            merge=request.param)
    eng.warmup()
    return eng


def _anchor_seeds(engine, anchors, revisits):
    """Certified seeds for ``revisits`` from exact engine answers at
    ``anchors`` — the same math the cache's seed pool applies."""
    dk, ids = engine.query(anchors)
    assert np.all(ids >= 0) and np.all(np.isfinite(dk))
    return certified_seeds(revisits, anchors, dk.astype(np.float32))


class TestCertifiedSeedMath:
    def test_seed_strictly_exceeds_bound_after_f32_squaring(self):
        """The parity requirement: f32(seed)**2 must be STRICTLY greater
        than the f32 square of any distance <= the f64 bound — a plain
        radius-domain nextafter fails this (both squares can round to
        the same f32), which is why the slack is multiplicative."""
        rng = np.random.default_rng(0)
        src_q = rng.random((64, 3)).astype(np.float32)
        src_dk = (rng.random(64) * 0.2).astype(np.float32)
        q = rng.random((128, 3)).astype(np.float32)
        seeds = certified_seeds(q, src_q, src_dk)
        q64, s64 = q.astype(np.float64), src_q.astype(np.float64)
        d = np.sqrt(((q64[:, None, :] - s64[None, :, :]) ** 2).sum(axis=2))
        bound = np.min(src_dk.astype(np.float64)[None, :] + d, axis=1)
        s2 = np.square(seeds).astype(np.float32)
        b2 = np.square(bound.astype(np.float32))
        assert np.all(s2 > b2)

    def test_distance_zero_anchor_floor(self):
        """An anchor identical to the query with dk == 0 must still
        produce a positive seed whose square is nonzero — otherwise the
        strict-< heap would reject the distance-0 candidate itself."""
        q = np.zeros((1, 3), np.float32)
        seeds = certified_seeds(q, q, np.zeros(1, np.float32))
        assert seeds[0] >= _SEED_FLOOR
        assert np.float32(seeds[0]) ** 2 > 0.0

    def test_seed_pool_ring_and_dim_guard(self):
        pool = SeedPool(4)
        for i in range(6):  # overwrite-oldest past capacity
            pool.add(np.full(3, i, np.float32), float(i))
        q, dk = pool.snapshot()
        assert len(q) == 4 and set(dk.tolist()) == {2.0, 3.0, 4.0, 5.0}
        pool.add(np.zeros(5, np.float32), 1.0)  # dim mismatch: ignored
        q2, _ = pool.snapshot()
        assert q2.shape == (4, 3)

    def test_empty_pool_returns_none(self):
        assert SeedPool(4).snapshot() is None


class TestSeededBitwiseResident:
    """seeded == unseeded, bit for bit, on merge=host AND merge=device
    (the fixture params), over adversarial probes."""

    def _probes(self, points, seed=3):
        rng = np.random.default_rng(seed)
        return [
            rng.random((40, 3)).astype(np.float32),
            points[[0, 1, 895, 896, 897]],  # the planted duplicates
            points[10:11],                   # single ragged row
        ]

    def test_identical_anchor_tightest_seed(self, merge_engine, points):
        """Revisit == anchor: the seed is one slack step above the TRUE
        kth distance — the tightest certified seed possible — and the
        planted duplicate rows put distance-0 ties at the boundary."""
        for q in self._probes(points):
            d0, n0 = merge_engine.query(q)
            seeds = _anchor_seeds(merge_engine, q, q)
            d1, n1 = merge_engine.query(q, seed_radius=seeds)
            assert d0.tobytes() == d1.tobytes()
            assert n0.tobytes() == n1.tobytes()

    def test_jittered_revisit_and_mixed_unseeded_rows(self, merge_engine,
                                                      points):
        """Near-duplicate revisits with HALF the rows left unseeded
        (+inf = the engine's unseeded sentinel) — one program family
        serves mixed batches; ragged rows pad inside the bucket."""
        rng = np.random.default_rng(5)
        anchors = rng.random((24, 3)).astype(np.float32)
        q = (anchors + rng.normal(0, 1e-3, anchors.shape)
             ).astype(np.float32)
        seeds = _anchor_seeds(merge_engine, anchors, q)
        seeds[::2] = np.inf
        d0, n0 = merge_engine.query(q)
        d1, n1 = merge_engine.query(q, seed_radius=seeds)
        assert d0.tobytes() == d1.tobytes()
        assert n0.tobytes() == n1.tobytes()

    def test_seeded_dispatch_compiles_nothing_new(self, merge_engine):
        """The per-query radius is a dynamic operand, not a trace
        constant: seeding an already-warm bucket must not compile."""
        rng = np.random.default_rng(7)
        q = rng.random((16, 3)).astype(np.float32)
        merge_engine.query(q)  # bucket warm
        before = merge_engine.compile_count
        seeds = _anchor_seeds(merge_engine, q, q)
        merge_engine.query(q, seed_radius=seeds)
        assert merge_engine.compile_count == before

    def test_seed_length_mismatch_raises(self, merge_engine):
        q = np.zeros((4, 3), np.float32)
        with pytest.raises(ValueError, match="seed_radius"):
            merge_engine.query(q, seed_radius=np.ones(3, np.float32))

    def test_finite_max_radius_clamps_seed(self, points):
        """Engine with finite max_radius: a seed above it is clamped by
        dispatch and the under-full rows keep the radius sentinel."""
        from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
        from mpi_cuda_largescaleknn_tpu.serve.engine import ResidentKnnEngine

        eng = ResidentKnnEngine(points, K, mesh=get_mesh(8),
                                engine="tiled", bucket_size=32,
                                max_batch=32, min_batch=8,
                                max_radius=0.05)
        rng = np.random.default_rng(9)
        q = rng.random((16, 3)).astype(np.float32)
        d0, n0 = eng.query(q)
        # seeds far above max_radius AND far below it, mixed
        seeds = np.full(16, np.inf, np.float32)
        seeds[:8] = np.float32(10.0)
        d1, n1 = eng.query(q, seed_radius=seeds)
        assert d0.tobytes() == d1.tobytes()
        assert n0.tobytes() == n1.tobytes()


class TestSeededBitwiseStreaming:
    def test_streaming_budget_matrix(self, points):
        """Seeded == unseeded across device budgets {1 slab, all}: the
        fold init starts at seed² but every slab a true candidate lives
        in is still visited, so promotions may shrink — bits may not."""
        from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
        from mpi_cuda_largescaleknn_tpu.serve.slabpool import (
            StreamingKnnEngine,
        )

        rng = np.random.default_rng(13)
        q = rng.random((24, 3)).astype(np.float32)
        for budget_slabs in (1, 0):  # 1-resident-slab squeeze, unbounded
            stream = StreamingKnnEngine(
                points=points, num_slabs=4, k=K, mesh=get_mesh(2),
                engine="tiled", bucket_size=64, max_batch=32,
                min_batch=16, merge="device")
            if budget_slabs:
                stream._pool.set_device_budget(stream.slab_device_bytes)
            try:
                d0, n0 = stream.query(q)
                seeds = _anchor_seeds(stream, q, q)
                d1, n1 = stream.query(q, seed_radius=seeds)
                assert d0.tobytes() == d1.tobytes()
                assert n0.tobytes() == n1.tobytes()
            finally:
                stream.close()


class TestSeededBitwiseRouted:
    @pytest.fixture(scope="class")
    def routed(self, points):
        """Two routed slab hosts + a RoutedPodFanout, overlap planted via
        the duplicate rows living in slab 0 while their copies end slab 1."""
        from mpi_cuda_largescaleknn_tpu.models.sharding import slab_bounds
        from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
        from mpi_cuda_largescaleknn_tpu.serve.engine import ResidentKnnEngine
        from mpi_cuda_largescaleknn_tpu.serve.frontend import (
            HostSliceServer,
            build_frontend,
        )

        servers = []
        for b, e in slab_bounds(len(points), 2):
            eng = ResidentKnnEngine(points[b:e], K, mesh=get_mesh(2),
                                    engine="tiled", bucket_size=64,
                                    max_batch=32, min_batch=16,
                                    id_offset=b, emit="candidates")
            eng.warmup()
            srv = HostSliceServer(("127.0.0.1", 0), eng, routing="bounds")
            threading.Thread(target=srv.serve_forever, daemon=True).start()
            srv.ready = True
            servers.append(srv)
        urls = [f"http://127.0.0.1:{s.server_address[1]}" for s in servers]
        front = build_frontend(urls, port=0, start_monitor=False)
        front.ready = True
        threading.Thread(target=front.serve_forever, daemon=True).start()
        yield front
        front.close()
        for s in servers:
            s.close()

    def test_routed_fanout_seeded_bitwise(self, routed, points):
        """The fan-out's escalation radius starts at the seed; the
        certified answer — including the exact flags — stays bitwise."""
        fanout = routed.fanout
        rng = np.random.default_rng(17)
        for q in (rng.random((20, 3)).astype(np.float32),
                  points[[0, 1, 895, 896]],  # cross-slab distance-0 ties
                  points[42:43]):
            d0, n0, e0 = fanout(q)
            assert np.all(e0)
            dk, ids, _ = fanout(q)
            seeds = certified_seeds(q, q, dk.astype(np.float32))
            d1, n1, e1 = fanout(q, seed_radius=seeds)
            assert d0.tobytes() == d1.tobytes()
            assert n0.tobytes() == n1.tobytes()
            assert np.array_equal(e0, e1)

    def test_frontend_http_hit_path_byte_identity(self, routed):
        """Same JSON body twice through the pod front end: the second is
        served from the cache — and the response bytes are identical."""
        base = f"http://127.0.0.1:{routed.server_address[1]}"
        rng = np.random.default_rng(19)
        body = json.dumps({
            "queries": rng.random((6, 3)).astype(np.float32).tolist(),
            "neighbors": True}).encode()

        def post():
            req = urllib.request.Request(
                base + "/knn", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=120) as resp:
                return resp.read()

        first, second = post(), post()
        assert first == second
        with urllib.request.urlopen(base + "/stats", timeout=30) as r:
            stats = json.loads(r.read())
        assert stats["qcache"]["hits"] >= 6
        with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
            metrics = r.read().decode()
        assert "knn_qcache_hits_total" in metrics
        assert "knn_qcache_seeds_total" in metrics


class TestCacheKeying:
    def _publish(self, qc, q, tenant=None, plan_token=None, dists=None,
                 ids=None):
        actions = qc.begin(q, plan_token, tenant)
        keys = [a[1] for a in actions if a[0] == "own"]
        d = dists if dists is not None else np.arange(len(q), dtype=np.float32)
        i = (ids if ids is not None
             else np.tile(np.arange(K, dtype=np.int32), (len(q), 1)))
        qc.publish(keys, (d, i), q, plan_token, tenant)
        return actions

    def test_tenants_keyed_apart(self):
        qc = QueryCache(capacity_rows=64, seed_rows=8)
        q = np.stack([np.ones(3, np.float32),
                      np.full(3, 2.0, np.float32)])
        self._publish(qc, q, tenant="a")
        actions_b = qc.begin(q, None, "b")
        assert all(a[0] == "own" for a in actions_b), "cross-tenant hit!"
        qc.abort([a[1] for a in actions_b if a[0] == "own"])
        hits = [a for a in qc.begin(q, None, "a") if a[0] == "hit"]
        assert len(hits) == 2
        # tenant twins: only tenant a has hit counters
        st = qc.stats()
        assert st["tenants"]["a"]["hits"] == 2
        assert st["tenants"].get("b", {}).get("hits", 0) == 0

    def test_plans_keyed_apart(self):
        from mpi_cuda_largescaleknn_tpu.serve.recall import DEFAULT_PLANS

        qc = QueryCache(capacity_rows=64, seed_rows=8)
        q = np.ones((1, 3), np.float32)
        tok = DEFAULT_PLANS[0].batch_key()
        self._publish(qc, q, plan_token=tok)
        assert qc.begin(q, None, None)[0][0] == "own"  # exact misses
        assert qc.begin(q, DEFAULT_PLANS[1].batch_key(), None)[0][0] == "own"
        assert qc.begin(q, tok, None)[0][0] == "hit"

    def test_generation_fences_reuse(self):
        qc = QueryCache(capacity_rows=64, seed_rows=8)
        q = np.ones((1, 3), np.float32)
        self._publish(qc, q)
        qc.invalidate()
        assert qc.begin(q, None, None)[0][0] == "own"
        assert qc.stats()["generation"] == 1

    def test_lru_eviction_bound(self):
        qc = QueryCache(capacity_rows=2, seed_rows=0)
        for v in range(3):
            self._publish(qc, np.full((1, 3), v, np.float32))
        st = qc.stats()
        assert st["size_rows"] == 2 and st["evictions"] == 1
        # the oldest row is the evicted one
        assert qc.begin(np.zeros((1, 3), np.float32), None, None)[0][0] \
            == "own"

    def test_degraded_rows_never_cached(self):
        qc = QueryCache(capacity_rows=64, seed_rows=8)
        q = np.ones((1, 3), np.float32)
        actions = qc.begin(q, None, None)
        keys = [a[1] for a in actions if a[0] == "own"]
        qc.publish(keys, (np.ones(1, np.float32),
                          np.zeros((1, K), np.int32),
                          np.zeros(1, bool)), q, None, None)
        assert qc.begin(q, None, None)[0][0] == "own"
        assert qc.stats()["inserts"] == 0

    def test_underfull_rows_never_feed_seed_pool(self):
        """A row with -1 pad ids (or an infinite kth distance) must not
        become a seed anchor — fullness is the soundness precondition."""
        qc = QueryCache(capacity_rows=64, seed_rows=8)
        q = np.ones((2, 3), np.float32)
        ids = np.tile(np.arange(K, dtype=np.int32), (2, 1))
        ids[0, -1] = -1
        d = np.array([1.0, np.inf], np.float32)
        self._publish(qc, q, dists=d, ids=ids)
        assert qc.seed_for(np.ones((1, 3), np.float32), None) is None

    def test_seed_rows_zero_disables_seeding_only(self):
        qc = QueryCache(capacity_rows=64, seed_rows=0)
        q = np.ones((1, 3), np.float32)
        self._publish(qc, q)
        assert qc.seed_for(q, None) is None
        assert qc.begin(q, None, None)[0][0] == "hit"


class TestInFlightDedup:
    def _batcher(self, fn, qc=None):
        from mpi_cuda_largescaleknn_tpu.serve.batcher import DynamicBatcher

        return DynamicBatcher(fn, max_batch=64, max_delay_s=0.001,
                              qcache=qc)

    def test_concurrent_identical_submitters_share_one_computation(self):
        """8 threads submit the same 4 rows; the engine must see far
        fewer than 32 rows and every thread gets identical bytes."""
        rows_seen = []
        gate = threading.Event()

        def query_fn(q):
            rows_seen.append(len(q))
            gate.wait(10)  # hold the owner so others join in flight
            return (np.linalg.norm(q, axis=1).astype(np.float32),
                    np.tile(np.arange(K, dtype=np.int32), (len(q), 1)))

        qc = QueryCache(capacity_rows=64, seed_rows=0)
        b = self._batcher(query_fn, qc)
        q = np.full((4, 3), 0.25, np.float32)
        results = [None] * 8

        def worker(i):
            if i == 7:
                gate.set()  # last thread releases the gate
            results[i] = b.submit(q, timeout_s=30)

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(30)
        b.shutdown()
        ref = results[0]
        for r in results[1:]:
            assert r[0].tobytes() == ref[0].tobytes()
            assert r[1].tobytes() == ref[1].tobytes()
        assert sum(rows_seen) < 32
        assert qc.stats()["dedup_rows"] + qc.stats()["hits"] > 0

    def test_owner_failure_wakes_joiners_who_retry(self):
        """The owner's batch fails once; joiners must NOT hang on the
        aborted entry — they retry as their own owners and succeed."""
        calls = {"n": 0}
        owner_in = threading.Event()
        release = threading.Event()

        def query_fn(q):
            calls["n"] += 1
            if calls["n"] == 1:
                owner_in.set()
                release.wait(10)
                raise RuntimeError("transient engine fault")
            return (np.zeros(len(q), np.float32),
                    np.tile(np.arange(K, dtype=np.int32), (len(q), 1)))

        qc = QueryCache(capacity_rows=64, seed_rows=0)
        b = self._batcher(query_fn, qc)
        q = np.full((2, 3), 0.5, np.float32)
        out = {}

        def owner():
            try:
                b.submit(q, timeout_s=30)
            except RuntimeError as e:
                out["owner_error"] = e

        def joiner():
            owner_in.wait(10)
            release.set()
            out["joiner"] = b.submit(q, timeout_s=30)

        t1 = threading.Thread(target=owner)
        t2 = threading.Thread(target=joiner)
        t1.start(); t2.start()
        t1.join(30); t2.join(30)
        b.shutdown()
        assert "owner_error" in out
        assert out["joiner"][0].shape == (2,)
        assert qc.stats()["inflight_aborts"] >= 1

    def test_intra_request_duplicates_coalesce(self):
        rows_seen = []

        def query_fn(q):
            rows_seen.append(len(q))
            return (np.linalg.norm(q, axis=1).astype(np.float32),
                    np.tile(np.arange(K, dtype=np.int32), (len(q), 1)))

        qc = QueryCache(capacity_rows=64, seed_rows=0)
        b = self._batcher(query_fn, qc)
        base = np.random.default_rng(3).random((4, 3)).astype(np.float32)
        q = np.concatenate([base, base, base[:2]])
        d, n = b.submit(q, timeout_s=30)
        b.shutdown()
        assert sum(rows_seen) == 4
        assert d[:4].tobytes() == d[4:8].tobytes()
        assert d[8:].tobytes() == d[:2].tobytes()
        assert n[:4].tobytes() == n[4:8].tobytes()
        assert qc.stats()["dedup_rows"] == 6


class TestServerHitPath:
    @pytest.fixture(scope="class")
    def server(self, points):
        from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
        from mpi_cuda_largescaleknn_tpu.serve.engine import ResidentKnnEngine
        from mpi_cuda_largescaleknn_tpu.serve.server import build_server

        eng = ResidentKnnEngine(points, K, mesh=get_mesh(8),
                                engine="tiled", bucket_size=32,
                                max_batch=64, min_batch=8)
        eng.warmup()
        srv = build_server(eng, port=0, max_delay_s=0.002)
        srv.ready = True
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        yield srv
        srv.close()

    def test_http_binary_hit_byte_identity(self, server):
        """Binary wire, same payload twice: the hit must replay the
        exact bytes AND count in /stats + /metrics with tenant twins."""
        base = f"http://127.0.0.1:{server.server_address[1]}"
        q = np.random.default_rng(23).random((5, 3)).astype(np.float32)

        def post():
            req = urllib.request.Request(
                base + "/knn?neighbors=1", data=q.tobytes(),
                headers={"Content-Type": "application/octet-stream"})
            with urllib.request.urlopen(req, timeout=60) as resp:
                return resp.read()

        first, second = post(), post()
        assert first == second
        with urllib.request.urlopen(base + "/stats", timeout=30) as r:
            stats = json.loads(r.read())
        qs = stats["qcache"]
        assert qs["hits"] >= 5 and qs["inserts"] >= 5
        with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
            metrics = r.read().decode()
        for name in ("knn_qcache_hits_total", "knn_qcache_seeds_total",
                     "knn_qcache_dedup_rows_total",
                     "knn_qcache_evictions_total"):
            assert name in metrics, f"missing {name}"

    def test_seeded_revisit_stream_matches_cold_server(self, server,
                                                       points):
        """Near-duplicate stream through the full server stack (cache
        warm, seeds engaged) vs the raw engine — byte-identical."""
        base = f"http://127.0.0.1:{server.server_address[1]}"
        rng = np.random.default_rng(29)
        anchors = rng.random((12, 3)).astype(np.float32)
        near = (anchors + rng.normal(0, 1e-3, anchors.shape)
                ).astype(np.float32)

        def post(q):
            req = urllib.request.Request(
                base + "/knn", data=json.dumps(
                    {"queries": q.tolist(), "neighbors": True}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=60) as resp:
                return json.loads(resp.read())

        post(anchors)  # warm the seed pool with full exact rows
        got = post(near)
        d_ref, n_ref = server.engine.query(near)
        np.testing.assert_array_equal(
            np.asarray(got["dists"], np.float32),
            d_ref.astype(np.float32))
        np.testing.assert_array_equal(np.asarray(got["neighbors"]), n_ref)
        assert server.qcache.stats()["seeds"] >= 1
