import jax.numpy as jnp
import numpy as np

from mpi_cuda_largescaleknn_tpu.ops.candidates import (
    current_worst_radius,
    extract_final_result,
    init_candidates,
    merge_candidates,
)


def test_init_default_is_inf():
    st = init_candidates(4, 3)
    assert np.all(np.isinf(st.dist2))
    assert np.all(np.array(st.idx) == -1)


def test_init_with_radius_holds_r2():
    st = init_candidates(2, 3, max_radius=2.0)
    np.testing.assert_array_equal(np.array(st.dist2), np.full((2, 3), 4.0, np.float32))


def test_merge_keeps_k_smallest_sorted():
    st = init_candidates(1, 3)
    st = merge_candidates(st, jnp.array([[5.0, 1.0, 3.0, 2.0]]),
                          jnp.array([[10, 11, 12, 13]], jnp.int32))
    np.testing.assert_array_equal(np.array(st.dist2[0]), [1.0, 2.0, 3.0])
    np.testing.assert_array_equal(np.array(st.idx[0]), [11, 13, 12])


def test_radius_cutoff_is_strict():
    # candidate exactly at r^2 must NOT displace the cutoff slot
    st = init_candidates(1, 2, max_radius=2.0)
    st = merge_candidates(st, jnp.array([[4.0, 1.0]]), jnp.array([[7, 8]], jnp.int32))
    np.testing.assert_array_equal(np.array(st.dist2[0]), [1.0, 4.0])
    np.testing.assert_array_equal(np.array(st.idx[0]), [8, -1])


def test_adopt_across_rounds_equals_single_merge():
    # two sequential merges == one merge of the union (cross-round heap adoption,
    # reference round>0 cutoff=-1 semantics)
    rng = np.random.default_rng(0)
    a = rng.random((5, 7), dtype=np.float32)
    b = rng.random((5, 9), dtype=np.float32)
    ia = np.arange(7, dtype=np.int32).reshape(1, -1).repeat(5, 0)
    ib = (100 + np.arange(9, dtype=np.int32)).reshape(1, -1).repeat(5, 0)
    st1 = merge_candidates(merge_candidates(init_candidates(5, 4), jnp.array(a), jnp.array(ia)),
                           jnp.array(b), jnp.array(ib))
    st2 = merge_candidates(init_candidates(5, 4),
                           jnp.concatenate([jnp.array(a), jnp.array(b)], axis=1),
                           jnp.concatenate([jnp.array(ia), jnp.array(ib)], axis=1))
    np.testing.assert_array_equal(np.array(st1.dist2), np.array(st2.dist2))


def test_extract_underfull_stays_inf():
    st = init_candidates(1, 3)
    st = merge_candidates(st, jnp.array([[1.0, 4.0]]), jnp.array([[0, 1]], jnp.int32))
    out = np.array(extract_final_result(st))
    assert out[0] == np.inf


def test_extract_sqrt_of_kth():
    st = init_candidates(1, 2)
    st = merge_candidates(st, jnp.array([[9.0, 4.0, 16.0]]), jnp.array([[0, 1, 2]], jnp.int32))
    np.testing.assert_allclose(np.array(extract_final_result(st)), [3.0])


def test_worst_radius_masks_padding():
    st = init_candidates(3, 1)
    st = merge_candidates(st, jnp.array([[4.0], [9.0], [1.0]]),
                          jnp.zeros((3, 1), jnp.int32))
    mask = jnp.array([True, False, True])  # middle row is a padded query
    assert float(current_worst_radius(st, mask)) == 2.0
    assert float(current_worst_radius(st)) == 3.0
