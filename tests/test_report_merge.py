"""Report-integrity of the targeted re-run modes.

`tools/tpu_tune.py --cells` and `benchmarks.py --only` both merge
re-measured rows into a checkpointed report that holds scarce on-chip
data — a merge bug silently destroys measurements a tunnel outage makes
unrepeatable. These tests drive the real main() entry points with the
child subprocess mocked (no jax, no tunnel), asserting the protection
properties: replace-by-identity, no duplicates, never clobber a good row
with a failure, stable ordering, honest top-level flags.
"""

import importlib.util
import json
import os
import sys
import types

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(name, path):
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture()
def tune(monkeypatch, tmp_path):
    mod = _load("tpu_tune_under_test", os.path.join(_REPO, "tools",
                                                    "tpu_tune.py"))
    monkeypatch.setattr(mod, "REPORT_PATH", str(tmp_path / "tune.json"))
    return mod


@pytest.fixture()
def suite(monkeypatch, tmp_path):
    mod = _load("benchmarks_under_test", os.path.join(_REPO,
                                                      "benchmarks.py"))
    monkeypatch.setattr(mod, "REPORT_PATH", str(tmp_path / "suite.json"))
    monkeypatch.setattr(mod, "_tpu_ok", lambda *a, **kw: False)
    return mod


def _fake_run(result_for):
    """subprocess.run stand-in: RESULT line per spec, or a failure."""
    def run(argv, **kw):
        spec = json.loads(argv[-1])
        out = result_for(spec)
        if out is None:
            return types.SimpleNamespace(returncode=1, stdout="",
                                         stderr="boom")
        return types.SimpleNamespace(
            returncode=0, stdout="RESULT " + json.dumps(out) + "\n",
            stderr="")
    return run


def test_cells_replaces_matching_spec_without_duplicates(tune, monkeypatch,
                                                         tmp_path):
    prior = [
        {"engine": "pallas_tiled", "n": 500, "k": 8, "bucket_size": 256,
         "point_group": 2, "env": {"LSK_CHUNK_LANES": "2048"}, "qps": 100.0},
        {"engine": "pallas_tiled", "n": 500, "k": 8, "bucket_size": 64,
         "env": {"LSK_CHUNK_LANES": "2048"}, "qps": 50.0},
        {"engine": "pallas_tiled", "n": 500, "k": 100, "bucket_size": 512,
         "env": {"LSK_CHUNK_LANES": "2048"}, "error": "timeout"},
    ]
    with open(tune.REPORT_PATH, "w") as f:
        json.dump(prior, f)
    cells = tmp_path / "cells.json"
    # re-measure the first spec (same identity, new qps)
    respec = {k: v for k, v in prior[0].items() if k != "qps"}
    cells.write_text(json.dumps([respec]))

    monkeypatch.setattr(
        tune.subprocess, "run",
        _fake_run(lambda s: {**s, "qps": 999.0, "platform": "tpu"}))
    monkeypatch.setattr(sys, "argv", ["tpu_tune.py", "--cells", str(cells)])
    assert tune.main() == 0

    rows = json.load(open(tune.REPORT_PATH))
    assert len([r for r in rows if r.get("bucket_size") == 256]) == 1
    assert [r["qps"] for r in rows if r.get("bucket_size") == 256] == [999.0]
    # untouched good row survives; prior error row is dropped
    assert any(r.get("bucket_size") == 64 and r["qps"] == 50.0 for r in rows)
    assert not any("error" in r for r in rows)


def test_cells_failed_rerun_keeps_prior_good_row(tune, monkeypatch,
                                                 tmp_path):
    prior = [
        {"engine": "pallas_tiled", "n": 500, "k": 8, "bucket_size": 64,
         "env": {"LSK_CHUNK_LANES": "2048"}, "qps": 50.0},
    ]
    with open(tune.REPORT_PATH, "w") as f:
        json.dump(prior, f)
    cells = tmp_path / "cells.json"
    cells.write_text(json.dumps(
        [{k: v for k, v in prior[0].items() if k != "qps"}]))

    monkeypatch.setattr(tune.subprocess, "run", _fake_run(lambda s: None))
    monkeypatch.setattr(sys, "argv", ["tpu_tune.py", "--cells", str(cells)])
    assert tune.main() == 0
    rows = json.load(open(tune.REPORT_PATH))
    assert [r["qps"] for r in rows] == [50.0]  # crash did not clobber


def test_cells_missing_path_is_usage_error(tune, monkeypatch):
    monkeypatch.setattr(sys, "argv", ["tpu_tune.py", "--cells"])
    assert tune.main() == 2


def test_only_merge_preserves_other_rows_and_order(suite, monkeypatch,
                                                   tmp_path):
    prior = {"full": True, "tpu_available": False, "results": [
        {"config": "unordered_1dev_k8", "queries_per_sec": 1.0},
        {"config": "unordered_1dev_k32", "queries_per_sec": 2.0},
        {"config": "unordered_1dev_k100", "error": "timeout"},
    ]}
    with open(suite.REPORT_PATH, "w") as f:
        json.dump(prior, f)

    monkeypatch.setattr(
        suite.subprocess, "run",
        _fake_run(lambda s: {"config": s["name"], "queries_per_sec": 42.0}))
    monkeypatch.setattr(sys, "argv",
                        ["benchmarks.py", "--full", "--only", "k100"])
    assert suite.main() == 0

    rep = json.load(open(suite.REPORT_PATH))
    names = [r["config"] for r in rep["results"]]
    # canonical order kept: k8, k32, k100 stay in config-list order
    assert names[:3] == ["unordered_1dev_k8", "unordered_1dev_k32",
                         "unordered_1dev_k100"]
    by = {r["config"]: r for r in rep["results"]}
    assert by["unordered_1dev_k100"]["queries_per_sec"] == 42.0
    assert by["unordered_1dev_k8"]["queries_per_sec"] == 1.0
    assert rep["full"] is True  # both runs --full: flag stays trustworthy


def test_only_failed_rerun_keeps_prior_good_row(suite, monkeypatch):
    prior = {"full": True, "tpu_available": False, "results": [
        {"config": "unordered_1dev_k100", "queries_per_sec": 7.0},
    ]}
    with open(suite.REPORT_PATH, "w") as f:
        json.dump(prior, f)

    monkeypatch.setattr(suite.subprocess, "run", _fake_run(lambda s: None))
    monkeypatch.setattr(sys, "argv",
                        ["benchmarks.py", "--full", "--only", "k100"])
    assert suite.main() == 0
    rep = json.load(open(suite.REPORT_PATH))
    row = [r for r in rep["results"]
           if r["config"] == "unordered_1dev_k100"][0]
    assert row.get("queries_per_sec") == 7.0  # crash did not clobber


def test_only_mode_disagreement_nulls_full_flag(suite, monkeypatch):
    prior = {"full": True, "tpu_available": False, "results": [
        {"config": "unordered_1dev_k8", "queries_per_sec": 1.0},
    ]}
    with open(suite.REPORT_PATH, "w") as f:
        json.dump(prior, f)
    monkeypatch.setattr(
        suite.subprocess, "run",
        _fake_run(lambda s: {"config": s["name"], "queries_per_sec": 3.0}))
    # quick-mode re-run into a full report
    monkeypatch.setattr(sys, "argv", ["benchmarks.py", "--only", "k8"])
    assert suite.main() == 0
    rep = json.load(open(suite.REPORT_PATH))
    assert rep["full"] is None


def test_only_usage_errors(suite, monkeypatch):
    monkeypatch.setattr(sys, "argv", ["benchmarks.py", "--only"])
    assert suite.main() == 2
    monkeypatch.setattr(sys, "argv", ["benchmarks.py", "--only", "--full"])
    assert suite.main() == 2
    monkeypatch.setattr(sys, "argv", ["benchmarks.py", "--only", "nomatch"])
    assert suite.main() == 2
