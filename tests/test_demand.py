import numpy as np
import pytest

from mpi_cuda_largescaleknn_tpu.core.config import KnnConfig
from mpi_cuda_largescaleknn_tpu.models.prepartitioned import PrePartitionedKNN
from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh

from .oracle import assert_dist_equal, kth_nn_dist, random_points


def _cfg(**kw):
    kw.setdefault("k", 8)
    kw.setdefault("query_tile", 128)
    kw.setdefault("point_tile", 128)
    return KnnConfig(**kw)


def _tiled_partitions(num, n_each, gap=10.0, seed=0):
    """Spatially separated clusters: cluster i lives at x-offset i*gap."""
    out = []
    for i in range(num):
        p = random_points(n_each, seed=seed + i)
        p[:, 0] += i * gap
        out.append(p)
    return out


def test_demand_matches_oracle_overlapping():
    # partitions drawn from the same unit cube: everyone needs everyone
    parts = [random_points(120, seed=10 + i) for i in range(8)]
    model = PrePartitionedKNN(_cfg(), mesh=get_mesh(8))
    got = model.run(parts)
    allp = np.concatenate(parts)
    for part, d in zip(parts, got):
        assert_dist_equal(d, kth_nn_dist(part, allp, 8))


def test_demand_early_exit_on_tiled_data():
    # far-separated clusters: after round 0 every heap is full with local
    # neighbors and every other shard's box is beyond the worst radius ->
    # the while_loop exits after round 1 (the reference's all-picks-are--1
    # global exit, prePartitionedDataVariant.cu:320-322)
    parts = _tiled_partitions(8, 100)
    model = PrePartitionedKNN(_cfg(k=4), mesh=get_mesh(8))
    got = model.run(parts)
    # far-separated clusters satisfy every heap in round 0: the pmax early
    # exit must fire immediately (a vacuous `< total rounds` bound would
    # not catch a broken keep_going predicate)
    assert model.last_stats["rounds"] == 1, model.last_stats
    assert model.last_stats["kernels_run"] == [1] * 8
    allp = np.concatenate(parts)
    for part, d in zip(parts, got):
        assert_dist_equal(d, kth_nn_dist(part, allp, 4))


def test_demand_rotation_gating_saves_bytes():
    # adjacent-overlap clusters (cluster i spans [0.85*i, 0.85*i + 1] in x):
    # every shard needs exactly its +-1 ring neighbors, nothing further —
    # the offset-2 box distance (0.7) clears even a corner query's
    # own-shard-only k-th radius (~0.45), so the gate's entry-radius
    # over-approximation still rules those arrivals out at round 1's entry.
    # Round 0 rotates both directions (entry radius is inf); at round 1 no
    # device needs any delivery beyond the copies already in flight, so
    # BOTH ppermutes are gated off — the ungated scheme would pay
    # 2 rotations/round/device = 4; the gated ring pays 2. Results must be
    # identical to the oracle (gating must never starve a visit).
    parts = _tiled_partitions(8, 200, gap=0.85, seed=60)
    model = PrePartitionedKNN(_cfg(), mesh=get_mesh(8))
    got = model.run(parts)
    st = model.last_stats
    assert st["rounds"] == 2, st
    assert st["rotations_run"] == [2] * 8, st
    # interior shards visit own + both neighbors; edge shards skip the
    # wrapped far neighbor
    assert all(2 <= n <= 3 for n in st["kernels_run"]), st
    allp = np.concatenate(parts)
    for part, d in zip(parts, got):
        assert_dist_equal(d, kth_nn_dist(part, allp, 8))


def test_demand_uneven_and_empty_partitions():
    parts = [random_points(50, seed=20), np.zeros((0, 3), np.float32),
             random_points(75, seed=21), random_points(10, seed=22)]
    model = PrePartitionedKNN(_cfg(k=5), mesh=get_mesh(4))
    got = model.run(parts)
    allp = np.concatenate(parts)
    assert got[1].shape == (0,)
    for part, d in zip(parts, got):
        if len(part):
            assert_dist_equal(d, kth_nn_dist(part, allp, 5))


def test_demand_partition_count_mismatch():
    with pytest.raises(ValueError, match="does not match mesh size"):
        PrePartitionedKNN(_cfg(), mesh=get_mesh(4)).run(
            [random_points(10)] * 3)


def test_demand_tree_engine():
    parts = [random_points(80, seed=30 + i) for i in range(4)]
    got = PrePartitionedKNN(_cfg(engine="tree"), mesh=get_mesh(4)).run(parts)
    allp = np.concatenate(parts)
    for part, d in zip(parts, got):
        assert_dist_equal(d, kth_nn_dist(part, allp, 8))


def test_demand_radius_semantics():
    parts = _tiled_partitions(4, 60, gap=5.0, seed=40)
    r = 0.25
    got = PrePartitionedKNN(_cfg(k=30, max_radius=r), mesh=get_mesh(4)).run(parts)
    allp = np.concatenate(parts)
    for part, d in zip(parts, got):
        assert_dist_equal(d, kth_nn_dist(part, allp, 30, max_radius=r))


def test_prepartitioned_query_chunk_matches_unchunked(tmp_path):
    """Chunked demand streaming (>=3 chunks) is byte-identical to the
    unchunked pipeline, early exit still fires per chunk, and a
    checkpointed relaunch resumes cleanly."""
    parts = _tiled_partitions(4, 100)  # npad 100 -> chunks of 32: 4 chunks
    want = PrePartitionedKNN(_cfg(k=4), mesh=get_mesh(4)).run(parts)

    model = PrePartitionedKNN(_cfg(k=4, query_chunk=32,
                                   checkpoint_dir=str(tmp_path / "ck")),
                              mesh=get_mesh(4))
    got = model.run(parts)
    for w, g in zip(want, got):
        np.testing.assert_array_equal(g, w)
    assert len(model.last_stats["rounds_per_chunk"]) == 4
    # far-separated clusters: every chunk exits after its first round
    assert model.last_stats["rounds"] == 1, model.last_stats


def test_prepartitioned_query_chunk_overlapping_oracle():
    # overlapping partitions: chunked result must still be globally exact,
    # including the neighbor-id path (return_candidates plumbing)
    parts = [random_points(70, seed=50 + i) for i in range(4)]
    model = PrePartitionedKNN(_cfg(k=6, query_chunk=24), mesh=get_mesh(4))
    got, idx = model.run(parts, return_neighbors=True)
    allp = np.concatenate(parts)
    for part, d, ix in zip(parts, got, idx):
        assert_dist_equal(d, kth_nn_dist(part, allp, 6))
        # ids index the global concatenation; distances ascend per row
        nd = np.linalg.norm(part[:, None, :] - allp[ix], axis=-1)
        assert np.all(np.diff(nd, axis=1) >= -1e-6)


def test_demand_chunked_radius_semantics():
    parts = _tiled_partitions(4, 60, gap=5.0, seed=41)
    r = 0.25
    got = PrePartitionedKNN(_cfg(k=30, max_radius=r, query_chunk=16),
                            mesh=get_mesh(4)).run(parts)
    allp = np.concatenate(parts)
    for part, d in zip(parts, got):
        assert_dist_equal(d, kth_nn_dist(part, allp, 30, max_radius=r))
