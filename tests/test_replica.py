"""Replica groups + slab handoff (serve/replica.py, PR "robustness").

Unit layer: deterministic spread-pick sequences (fixed seed, no RNG),
replica grouping/validation as a pure function, and the handoff manager
driven with fake transports + explicit ``check_once`` calls — no HTTP,
no sleeps (the PR-8 monitor discipline).

Integration layer: a 2-slab x 2-replica routed pod (replicas of a slab
share one engine in-process — byte-equality between originals is then
trivially true, which makes the ADOPTED engine the real parity subject:
it re-materializes the slab from a surviving replica / the source file
and must serve the same bytes). The acceptance bars from the issue:
single-replica loss stays exact AND bit-identical (capacity, not
exactness), all-replicas-down degrades per the PR-8 contract, a
fingerprint-mismatched adoption never serves, and post-handoff queries
are bitwise-equal to a never-failed reference.
"""

from __future__ import annotations

import json
import tempfile
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

K = 5


def _post_knn(url, q, timeout=120):
    req = urllib.request.Request(
        url + "/knn",
        data=json.dumps({"queries": np.asarray(q).tolist(),
                         "neighbors": True}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _post_json(url, path, obj, timeout=30):
    req = urllib.request.Request(
        url + path, data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _replica_points():
    """600 rows: [0:300) cluster A in [0, 0.4)^3, [300:600) cluster B in
    [0.6, 1.0)^3 — disjoint slabs, so routing decisions are clean."""
    from tests.oracle import random_points

    a = random_points(300, seed=61, scale=0.4)
    b = random_points(300, seed=62, scale=0.4) + np.float32(0.6)
    return np.concatenate([a, b]).astype(np.float32)


# ---------------------------------------------------------------- unit layer


def _endpoints(urls, **health_kw):
    from mpi_cuda_largescaleknn_tpu.serve.frontend import _HostEndpoint

    health_kw.setdefault("fail_threshold", 1)
    health_kw.setdefault("jitter", 0.0)
    return [_HostEndpoint(u, dict(health_kw)) for u in urls]


class TestReplicaSet:
    def _set(self, urls=("http://a", "http://b"), seed=0):
        from mpi_cuda_largescaleknn_tpu.serve.replica import ReplicaSet

        eps = _endpoints(urls)
        groups = [{"row_offset": 0, "n_points": 10, "urls": list(urls)}]
        return ReplicaSet(eps, groups, seed=seed), eps

    def test_pick_is_deterministic_and_spreads(self):
        rs1, _ = self._set(seed=7)
        rs2, _ = self._set(seed=7)
        seq1 = [rs1.pick(0) for _ in range(8)]
        seq2 = [rs2.pick(0) for _ in range(8)]
        assert seq1 == seq2  # fixed seed -> identical pick sequence
        # the least-picked rule spreads: after warm-up the picks alternate
        assert set(seq1) == {0, 1}
        counts = [seq1.count(i) for i in (0, 1)]
        assert counts == [4, 4]
        # a different seed may start on the other replica but still spreads
        rs3, _ = self._set(seed=8)
        seq3 = [rs3.pick(0) for _ in range(8)]
        assert [seq3.count(i) for i in (0, 1)] == [4, 4]

    def test_pick_skips_drained_and_respects_batch_budget(self):
        rs, eps = self._set()
        eps[0].health.force_drain("down")
        assert all(rs.pick(0) == 1 for _ in range(4))
        # per-batch penalties deprioritize a just-failed replica...
        rs2, _ = self._set()
        assert rs2.pick(0, penalties={0: 1}, budget=2) == 1
        # ...and exclude it entirely once over budget
        rs3, eps3 = self._set()
        eps3[1].health.force_drain("down")
        assert rs3.pick(0, penalties={0: 3}, budget=2) is None

    def test_live_mask_counts_and_rebind(self):
        from mpi_cuda_largescaleknn_tpu.serve.frontend import _HostEndpoint
        from mpi_cuda_largescaleknn_tpu.serve.replica import ReplicaSet

        eps = _endpoints(["http://a", "http://b", "http://c"])
        rs = ReplicaSet(eps, [
            {"row_offset": 0, "n_points": 5,
             "urls": ["http://a", "http://b"]},
            {"row_offset": 5, "n_points": 5, "urls": ["http://c"]}])
        assert rs.num_slabs == 2
        assert rs.live_counts() == [2, 1]
        eps[0].health.force_drain("x")
        eps[2].health.force_drain("x")
        assert rs.live_counts() == [1, 0]
        assert rs.slab_live_mask().tolist() == [True, False]
        # runtime re-bind: a new endpoint joins slab 1's member set
        eps.append(_HostEndpoint("http://d", {"fail_threshold": 1}))
        rs.rebind(1, 3)
        assert rs.live_counts() == [1, 1]
        st = rs.stats()
        assert st["rebinds"] == 1
        assert st["per_slab"][1]["members"] == ["http://c", "http://d"]

    def test_groups_must_cover_and_not_overlap(self):
        from mpi_cuda_largescaleknn_tpu.serve.replica import ReplicaSet

        eps = _endpoints(["http://a", "http://b"])
        with pytest.raises(ValueError, match="cover"):
            ReplicaSet(eps, [{"row_offset": 0, "n_points": 5,
                              "urls": ["http://a"]}])
        with pytest.raises(ValueError, match="more than one"):
            ReplicaSet(eps, [
                {"row_offset": 0, "n_points": 5, "urls": ["http://a"]},
                {"row_offset": 5, "n_points": 5,
                 "urls": ["http://a", "http://b"]}])


class TestGroupRoutedHosts:
    def _stats(self, off, n, **extra):
        e = {"row_offset": off, "n_points": n, "k": K, "dim": 3,
             "shard_bounds": [{"lo": [0, 0, 0], "hi": [1, 1, 1],
                               "count": n}]}
        e.update(extra)
        return e

    def test_replica_grouping_and_slab_major_order(self):
        from mpi_cuda_largescaleknn_tpu.serve.health import host_fingerprint
        from mpi_cuda_largescaleknn_tpu.serve.replica import (
            group_routed_hosts,
        )

        urls = ["u-b0", "u-a0", "u-a1", "u-b1"]
        stats = [self._stats(300, 300), self._stats(0, 300),
                 self._stats(0, 300), self._stats(300, 300)]
        fps = {u: host_fingerprint(e, "bounds")
               for u, e in zip(urls, stats)}
        g = group_routed_hosts(urls, stats, fps)
        assert g["n_points"] == 600
        assert [s["row_offset"] for s in g["slabs"]] == [0, 300]
        assert g["slabs"][0]["urls"] == ["u-a0", "u-a1"]
        assert g["slabs"][1]["urls"] == ["u-b0", "u-b1"]
        assert g["host_urls"] == ["u-a0", "u-a1", "u-b0", "u-b1"]
        assert len(g["bounds_hosts"]) == 2  # one entry per SLAB
        assert g["slab_fingerprints"][0] == fps["u-a0"]

    def test_replica_fingerprint_mismatch_rejected(self):
        from mpi_cuda_largescaleknn_tpu.serve.health import host_fingerprint
        from mpi_cuda_largescaleknn_tpu.serve.replica import (
            group_routed_hosts,
        )

        urls = ["u-a0", "u-a1"]
        stats = [self._stats(0, 300, bucket_size=64),
                 self._stats(0, 300, bucket_size=32)]
        fps = {u: host_fingerprint(e, "bounds")
               for u, e in zip(urls, stats)}
        with pytest.raises(ValueError, match="replica mismatch") as ei:
            group_routed_hosts(urls, stats, fps)
        assert "bucket_size" in str(ei.value)

    def test_slab_gap_still_a_hard_error(self):
        from mpi_cuda_largescaleknn_tpu.serve.health import host_fingerprint
        from mpi_cuda_largescaleknn_tpu.serve.replica import (
            group_routed_hosts,
        )

        urls = ["u-b0"]
        stats = [self._stats(300, 300)]
        fps = {u: host_fingerprint(e, "bounds")
               for u, e in zip(urls, stats)}
        with pytest.raises(ValueError, match="tile the index"):
            group_routed_hosts(urls, stats, fps)


def _fake_routed_fanout(urls, groups):
    """A REAL RoutedPodFanout (no HTTP happens at construction) over fake
    bounds — what the manager unit tests drive."""
    from mpi_cuda_largescaleknn_tpu.serve.frontend import (
        PodBoundsTable,
        RoutedPodFanout,
    )

    bounds = PodBoundsTable([
        {"row_offset": g["row_offset"], "n_points": g["n_points"],
         "shards": [{"lo": [0, 0, 0], "hi": [1, 1, 1],
                     "count": g["n_points"]}]} for g in groups], dim=3)
    return RoutedPodFanout(
        urls, k=K, max_batch=32, bounds=bounds, replica_groups=groups,
        health_config={"fail_threshold": 1, "jitter": 0.0})


class TestReplicaManagerUnit:
    def _harness(self, *, stats_engine, probes, adopts):
        from mpi_cuda_largescaleknn_tpu.serve.health import host_fingerprint
        from mpi_cuda_largescaleknn_tpu.serve.replica import ReplicaManager

        groups = [{"row_offset": 0, "n_points": 300, "urls": ["http://a"]},
                  {"row_offset": 300, "n_points": 300,
                   "urls": ["http://b"]}]
        fan = _fake_routed_fanout(["http://a", "http://b"], groups)
        want = host_fingerprint(
            {"row_offset": 300, "n_points": 300, "k": K}, "bounds")
        registry = {}
        mgr = ReplicaManager(
            fan, slabs=groups, slab_fingerprints=[None, want],
            standbys=["http://sb"], handoff_floor=1,
            probe_fn=lambda url: probes[url].pop(0),
            stats_fn=lambda url: {"engine": stats_engine},
            adopt_fn=lambda url, req: adopts.append((url, req)) or {},
            fingerprint_registry=registry, clock=lambda: 0.0)
        return fan, mgr, want, registry

    def test_handoff_triggers_validates_and_binds(self):
        adopts = []
        probes = {"http://sb": [(False, {"status": "adopting"}),
                                (True, {"status": "ok"})]}
        fan, mgr, want, registry = self._harness(
            stats_engine={"row_offset": 300, "n_points": 300, "k": K},
            probes=probes, adopts=adopts)
        try:
            fan.endpoints[1].health.force_drain("died")
            assert fan.replicas.live_counts() == [1, 0]
            mgr.check_once(now=0.0)  # below floor -> adoption starts
            assert len(adopts) == 1
            url, req = adopts[0]
            assert url == "http://sb"
            assert req["host_id"] == 1 and req["num_hosts"] == 2
            assert req["row_offset"] == 300 and req["n_points"] == 300
            assert "source_url" not in req  # no live member to pull from
            assert mgr.stats()["inflight_slabs"] == [1]
            mgr.check_once(now=1.0)  # standby still materializing
            assert mgr.stats()["standbys"][0]["state"] == "adopting"
            mgr.check_once(now=2.0)  # ready -> fingerprint ok -> bound
            st = mgr.stats()
            assert st["handoffs"] == 1 and st["inflight_slabs"] == []
            assert st["standbys"][0]["state"] == "bound"
            assert len(fan.endpoints) == 3
            assert fan.replicas.live_counts() == [1, 1]
            assert registry["http://sb"] == want  # rejoin gate armed
            # no repeat adoption while the floor is satisfied
            probes["http://sb"].append((True, {"status": "ok"}))
            mgr.check_once(now=3.0)
            assert mgr.stats()["handoffs"] == 1 and len(adopts) == 1
        finally:
            fan.close()

    def test_fingerprint_mismatch_never_binds(self):
        adopts = []
        probes = {"http://sb": [(True, {"status": "ok"})]}
        # the standby came back serving the WRONG slab (row_offset 0)
        fan, mgr, _want, registry = self._harness(
            stats_engine={"row_offset": 0, "n_points": 300, "k": K},
            probes=probes, adopts=adopts)
        try:
            fan.endpoints[1].health.force_drain("died")
            mgr.check_once(now=0.0)
            mgr.check_once(now=1.0)
            st = mgr.stats()
            assert st["handoff_rejections"] == 1 and st["handoffs"] == 0
            sb = st["standbys"][0]
            assert sb["state"] == "failed"
            assert "fingerprint mismatch" in sb["last_error"]
            assert "row_offset" in sb["last_error"]  # the diff is named
            # the slab stays down: nothing was bound, nothing serves
            assert len(fan.endpoints) == 2
            assert fan.replicas.live_counts() == [1, 0]
            assert "http://sb" not in registry
        finally:
            fan.close()

    def test_adopt_failure_and_starvation_are_counted(self):
        def boom(url, req):
            raise OSError("connection refused")

        from mpi_cuda_largescaleknn_tpu.serve.replica import ReplicaManager

        groups = [{"row_offset": 0, "n_points": 300, "urls": ["http://a"]}]
        fan = _fake_routed_fanout(["http://a"], groups)
        try:
            mgr = ReplicaManager(
                fan, slabs=groups, slab_fingerprints=[None],
                standbys=["http://sb"], handoff_floor=1,
                probe_fn=lambda url: (False, {}),
                stats_fn=lambda url: {}, adopt_fn=boom,
                clock=lambda: 0.0)
            fan.endpoints[0].health.force_drain("died")
            mgr.check_once(now=0.0)
            st = mgr.stats()
            assert st["handoff_failures"] == 1
            assert st["standbys"][0]["state"] == "failed"
            assert "adopt request failed" in st["standbys"][0]["last_error"]
            mgr.check_once(now=1.0)  # no idle standby left
            assert mgr.stats()["starved"] == 1
        finally:
            fan.close()


# --------------------------------------------------------- integration layer


@pytest.fixture(scope="module")
def replica_pod(tmp_path_factory):
    """2 slabs x 2 replicas over disjoint clusters. Replicas of a slab
    share ONE engine in-process (replicas are byte-interchangeable by
    contract, so this is exact); the source file rides along for the
    standby's re-materialization path."""
    from mpi_cuda_largescaleknn_tpu.models.sharding import slab_bounds
    from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
    from mpi_cuda_largescaleknn_tpu.serve.engine import ResidentKnnEngine
    from mpi_cuda_largescaleknn_tpu.serve.frontend import HostSliceServer

    points = _replica_points()
    pts_path = str(tmp_path_factory.mktemp("replica") / "points.float3")
    points.tofile(pts_path)
    engines, servers = [], []
    for b, e in slab_bounds(len(points), 2):
        eng = ResidentKnnEngine(points[b:e], K, mesh=get_mesh(1),
                                engine="tiled", bucket_size=64,
                                max_batch=32, min_batch=16,
                                id_offset=b, emit="candidates")
        eng.warmup()
        engines.append(eng)
    for eng in engines:          # slab-major: A0, A1, B0, B1
        for _ in range(2):
            srv = HostSliceServer(("127.0.0.1", 0), eng, routing="bounds")
            threading.Thread(target=srv.serve_forever,
                             daemon=True).start()
            srv.ready = True
            servers.append(srv)
    urls = [f"http://127.0.0.1:{s.server_address[1]}" for s in servers]
    yield urls, points, servers, pts_path
    for s in servers:
        s.close()


@pytest.fixture(scope="module")
def reference_engine():
    from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
    from mpi_cuda_largescaleknn_tpu.serve.engine import ResidentKnnEngine

    eng = ResidentKnnEngine(_replica_points(), K, mesh=get_mesh(1),
                            engine="tiled", bucket_size=64,
                            max_batch=32, min_batch=16)
    eng.warmup()
    return eng


@pytest.fixture()
def clean_faults(replica_pod):
    _, _, servers, _ = replica_pod
    for s in servers:
        s.faults.clear()
    yield
    for s in servers:
        s.faults.clear()


def _build_fe(urls, **kw):
    from mpi_cuda_largescaleknn_tpu.serve.frontend import build_frontend

    kw.setdefault("on_host_loss", "degrade")
    kw.setdefault("retries", 1)
    kw.setdefault("retry_backoff_s", 0.001)
    kw.setdefault("fail_threshold", 2)
    kw.setdefault("start_monitor", False)
    srv = build_frontend(urls, port=0, pipeline_depth=2, **kw)
    srv.ready = True
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, f"http://127.0.0.1:{srv.server_address[1]}"


def _standby(pts_path, **overrides):
    from mpi_cuda_largescaleknn_tpu.serve.frontend import HostSliceServer

    cfg = dict(path=pts_path, num_hosts=2, k=K, shards=1, engine="tiled",
               bucket_size=64, max_batch=32, min_batch=16)
    cfg.update(overrides)
    srv = HostSliceServer(("127.0.0.1", 0), None, routing="bounds",
                          standby_config=cfg)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, f"http://127.0.0.1:{srv.server_address[1]}"


def _wait_adopt(standby, want="adopted", timeout_s=180.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        snap = standby.adopt_snapshot()
        if snap["state"] == want:
            return snap
        if want == "adopted" and snap["state"] == "failed":
            raise AssertionError(f"adoption failed: {snap['error']}")
        time.sleep(0.05)
    raise AssertionError(f"adoption never reached {want!r}: "
                         f"{standby.adopt_snapshot()}")


class TestReplicaGroupsServing:
    def test_grouped_frontend_serves_bitwise_and_spreads(
            self, replica_pod, reference_engine, clean_faults):
        from tests.oracle import random_points

        urls, _points, _servers, _ = replica_pod
        fe, base = _build_fe(urls)
        try:
            st = fe.fanout.stats()["routing"]["replicas"]
            assert st["num_slabs"] == 2
            assert [len(p["members"]) for p in st["per_slab"]] == [2, 2]
            for n in (1, 7, 16):
                q = random_points(n, seed=400 + n)
                resp = _post_knn(base, q)
                assert resp["exact"] is True
                want_d, want_n = reference_engine.query(q)
                np.testing.assert_array_equal(
                    np.asarray(resp["dists"], np.float32), want_d)
                np.testing.assert_array_equal(
                    np.asarray(resp["neighbors"], np.int32), want_n)
            # the spread counters show picks landing on BOTH replicas
            spread = fe.fanout.stats()["routing"]["replicas"]["spread"]
            assert sum(1 for v in spread.values() if v > 0) >= 2
            m = urllib.request.urlopen(base + "/metrics",
                                       timeout=30).read().decode()
            assert 'knn_replica_live{slab="0"} 2' in m
            assert "knn_replica_spread{" in m
            assert "knn_handoffs_total 0" in m
        finally:
            fe.close()

    def test_single_replica_loss_costs_capacity_not_exactness(
            self, replica_pod, reference_engine, clean_faults):
        from tests.oracle import random_points

        urls, _points, servers, _ = replica_pod
        fe, base = _build_fe(urls)
        try:
            servers[1].faults.set_specs("drop:")  # slab A, replica 1
            # EVERY query stays exact and bit-identical — the drained
            # replica is routed around, never degraded
            for seed in (81, 82):
                q = random_points(16, seed=seed)  # spans A, B, the gap
                resp = _post_knn(base, q)
                assert resp["exact"] is True
                assert "exact_per_query" not in resp
                want_d, want_n = reference_engine.query(q)
                np.testing.assert_array_equal(
                    np.asarray(resp["dists"], np.float32), want_d)
                np.testing.assert_array_equal(
                    np.asarray(resp["neighbors"], np.int32), want_n)
            # the spread policy routes AROUND a suspect replica (a single
            # dispatch failure is enough to deprioritize it), so dispatch
            # alone may never push it to drained — the monitor's probes
            # finish the job
            fe.monitor.check_once(now=1e9)
            fe.monitor.check_once(now=2e9)
            assert fe.fanout.endpoints[1].health.state == "drained"
            st = fe.fanout.stats()["routing"]["replicas"]
            assert st["per_slab"][0]["live"] == 1
            m = urllib.request.urlopen(base + "/metrics",
                                       timeout=30).read().decode()
            assert 'knn_replica_live{slab="0"} 1' in m
            # and queries after the drain STILL stay exact + bitwise
            q = random_points(16, seed=86)
            resp = _post_knn(base, q)
            assert resp["exact"] is True
            want_d, want_n = reference_engine.query(q)
            np.testing.assert_array_equal(
                np.asarray(resp["dists"], np.float32), want_d)
            np.testing.assert_array_equal(
                np.asarray(resp["neighbors"], np.int32), want_n)
        finally:
            fe.close()

    def test_all_replicas_down_degrades_then_rejoins(
            self, replica_pod, reference_engine, clean_faults):
        from tests.oracle import random_points

        urls, points, servers, _ = replica_pod
        fe, base = _build_fe(urls)
        try:
            servers[2].faults.set_specs("drop:")  # both replicas of B
            servers[3].faults.set_specs("drop:")
            qb = random_points(8, seed=83, scale=0.4) + np.float32(0.6)
            resp_b = _post_knn(base, qb)
            # zero live replicas for an improving slab: the PR-8 contract
            assert resp_b["exact"] is False
            assert resp_b["exact_per_query"] == [False] * len(qb)
            from tests.oracle import kth_nn_dist

            np.testing.assert_allclose(
                np.asarray(resp_b["dists"], np.float32),
                kth_nn_dist(qb, points[:300], K), rtol=5e-7, atol=1e-37)
            # A queries never touched slab B: still bit-identical
            qa = random_points(8, seed=84, scale=0.4)
            resp_a = _post_knn(base, qa)
            assert resp_a["exact"] is True
            want_d, want_n = reference_engine.query(qa)
            np.testing.assert_array_equal(
                np.asarray(resp_a["dists"], np.float32), want_d)
            st = fe.fanout.stats()["routing"]["replicas"]
            assert st["per_slab"][1]["live"] == 0
            # outage over: rejoin both, exactness returns
            servers[2].faults.clear()
            servers[3].faults.clear()
            fe.monitor.check_once(now=1e9)
            assert (fe.fanout.stats()["routing"]["replicas"]
                    ["per_slab"][1]["live"]) == 2
            resp_b2 = _post_knn(base, qb)
            assert resp_b2["exact"] is True
            want_d, want_n = reference_engine.query(qb)
            np.testing.assert_array_equal(
                np.asarray(resp_b2["dists"], np.float32), want_d)
            np.testing.assert_array_equal(
                np.asarray(resp_b2["neighbors"], np.int32), want_n)
        finally:
            fe.close()


class TestSlabHandoff:
    def test_handoff_end_to_end_with_query_during_and_parity_after(
            self, replica_pod, reference_engine, clean_faults):
        from tests.oracle import random_points

        urls, _points, servers, pts_path = replica_pod
        standby, sb_url = _standby(pts_path)
        fe, base = _build_fe(urls, standbys=[sb_url], handoff_floor=2)
        try:
            probe = random_points(24, seed=85)  # spans A, B, the gap
            before = _post_knn(base, probe)
            assert before["exact"] is True
            # kill slab A's replica 1; drive the monitor until it drains
            servers[1].faults.set_specs("drop:")
            fe.monitor.check_once(now=1e9)
            fe.monitor.check_once(now=2e9)
            assert fe.fanout.endpoints[1].health.state == "drained"
            # below the floor (live 1 < 2): the handoff started; queries
            # DURING the handoff keep serving bit-identical off the
            # surviving replica
            mid = _post_knn(base, probe)
            assert mid["exact"] is True
            assert mid["dists"] == before["dists"]
            assert mid["neighbors"] == before["neighbors"]
            snap = _wait_adopt(standby)  # pull-from-replica + warmup
            assert snap["slab"] == 0 and snap["seconds"] is not None
            # next monitor cycle: fingerprint-gate + bind
            fe.monitor.check_once(now=3e9)
            ho = fe.monitor.stats()["handoff"]
            assert ho["handoffs"] == 1 and ho["handoff_rejections"] == 0
            st = fe.fanout.stats()["routing"]["replicas"]
            assert st["per_slab"][0]["live"] == 2
            assert sb_url in st["per_slab"][0]["members"]
            assert st["rebinds"] == 1
            # now kill the OTHER original replica: slab A is served
            # EXCLUSIVELY by the adopted standby — the parity acceptance
            servers[0].faults.set_specs("drop:")
            fe.monitor.check_once(now=4e9)
            fe.monitor.check_once(now=5e9)
            assert fe.fanout.endpoints[0].health.state == "drained"
            after = _post_knn(base, probe)
            assert after["exact"] is True
            assert after["dists"] == before["dists"]
            assert after["neighbors"] == before["neighbors"]
            want_d, want_n = reference_engine.query(probe)
            np.testing.assert_array_equal(
                np.asarray(after["dists"], np.float32), want_d)
            np.testing.assert_array_equal(
                np.asarray(after["neighbors"], np.int32), want_n)
            m = urllib.request.urlopen(base + "/metrics",
                                       timeout=30).read().decode()
            assert "knn_handoffs_total 1" in m
            assert "knn_replica_rebinds_total 1" in m
        finally:
            fe.close()
            standby.close()

    def test_mismatched_standby_is_rejected_and_never_serves(
            self, replica_pod, clean_faults):
        urls, _points, servers, pts_path = replica_pod
        # wrong engine config: the adopted slab's fingerprint cannot match
        standby, sb_url = _standby(pts_path, bucket_size=32)
        fe, _base = _build_fe(urls, standbys=[sb_url], handoff_floor=2)
        try:
            servers[1].faults.set_specs("drop:")
            fe.monitor.check_once(now=1e9)
            fe.monitor.check_once(now=2e9)
            _wait_adopt(standby)  # adoption itself succeeds...
            fe.monitor.check_once(now=3e9)
            ho = fe.monitor.stats()["handoff"]
            # ...but the fingerprint gate refuses to bind it
            assert ho["handoffs"] == 0 and ho["handoff_rejections"] == 1
            sb = ho["standbys"][0]
            assert sb["state"] == "failed"
            assert "fingerprint mismatch" in sb["last_error"]
            assert "bucket_size" in sb["last_error"]
            st = fe.fanout.stats()["routing"]["replicas"]
            assert st["per_slab"][0]["live"] == 1  # still under-replicated
            assert sb_url not in st["per_slab"][0]["members"]
        finally:
            fe.close()
            standby.close()

    def test_adopt_slab_http_surface(self, replica_pod, clean_faults):
        urls, _points, _servers, pts_path = replica_pod
        # a regular routed host refuses adoption outright
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post_json(urls[0], "/adopt_slab", {"host_id": 0})
        assert ei.value.code == 409
        standby, sb_url = _standby(pts_path)
        try:
            # standby /healthz reports the lifecycle while empty
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(sb_url + "/healthz", timeout=30)
            assert ei.value.code == 503
            body = json.loads(ei.value.read())
            assert body["role"] == "standby"
            assert body["status"] == "standby"
            # malformed requests 400 without touching the lifecycle
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post_json(sb_url, "/adopt_slab", {"host_id": 9})
            assert ei.value.code == 400
            assert standby.adopt_snapshot()["state"] == "standby"
            # a valid file-path adoption materializes the slab and serves
            status, resp = _post_json(sb_url, "/adopt_slab",
                                      {"host_id": 0, "num_hosts": 2,
                                       "row_offset": 0, "n_points": 300})
            assert status == 202 and resp["status"] == "adopting"
            # adopting/adopted: a second adopt is refused (409)
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post_json(sb_url, "/adopt_slab", {"host_id": 1})
            assert ei.value.code == 409
            _wait_adopt(standby)
            assert standby.engine.n_points == 300
            assert standby.engine.id_offset == 0
            with urllib.request.urlopen(sb_url + "/healthz",
                                        timeout=30) as r:
                hz = json.loads(r.read())
            assert hz["status"] == "ok" and hz["role"] == "host-routed"
            assert hz["adopt"]["state"] == "adopted"
        finally:
            standby.close()

    def test_adoption_failure_is_surfaced_and_retryable(self, replica_pod):
        _urls, _points, _servers, pts_path = replica_pod
        standby, sb_url = _standby("/nonexistent/points.float3")
        try:
            status, _ = _post_json(sb_url, "/adopt_slab",
                                   {"host_id": 0, "num_hosts": 2})
            assert status == 202
            _wait_adopt(standby, want="failed")
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(sb_url + "/healthz", timeout=30)
            assert ei.value.code == 503
            body = json.loads(ei.value.read())
            assert body["status"] == "adopt-failed"
            assert "adopt_error" in body
            # a failed standby may retry (e.g. after the operator fixes
            # the file) — the 202 proves the lifecycle reopens
            status, _ = _post_json(sb_url, "/adopt_slab",
                                   {"host_id": 0, "num_hosts": 2})
            assert status == 202
        finally:
            standby.close()

    def test_slab_rows_pull_surface(self, replica_pod, clean_faults):
        urls, points, _servers, _ = replica_pod
        from mpi_cuda_largescaleknn_tpu.serve.replica import pull_slab_rows

        rows, off = pull_slab_rows(urls[2])  # slab B, replica 0
        assert off == 300
        np.testing.assert_array_equal(rows, points[300:])
