"""Multi-index tenancy (serve/tenancy.py): many indexes, one byte budget.

Five layers of coverage:

- ``TenantSpec`` / ``TenantRegistry`` config + routing-table units: name
  validation (tenant names ride in URLs), sorted enumeration, and the
  404 contract (``UnknownTenantError``, never a silent fallthrough to
  someone else's index).
- ``TenantQuotas`` admission slices with a no-jax controller: per-tenant
  caps over one global row budget, rollback of the tenant reservation
  when the GLOBAL cap rejects, 0 = unsliced, Retry-After surfaced on the
  raised ``OverloadError``, and the stats shape the /stats quota block
  serializes.
- Shared ``SlabPool`` with FAKE engines (no jax, no sleeps): (tenant,
  slab) tuple keys routing to each tenant's registered source + factory,
  per-tenant hit/promotion/eviction/stall accounting, and eviction
  FAIRNESS — a hot tenant's recently-touched pages survive a cold
  tenant's churn through the same budget.
- ``MultiTenantEngine`` with real engines: per-tenant BITWISE parity
  against isolated single-tenant ``StreamingKnnEngine`` twins across a
  device-budget matrix (the exactness contract: the shared pool changes
  WHEN a slab is resident, never what its engine computes), and the
  compile-count-flat gate — ≥3 tenants warm up through ONE shared
  executable cache at a single tenant's compile cost.
- HTTP surface through a real ``KnnServer``: ``/v1/<tenant>/knn`` (plus
  the ``tenant`` JSON field and ``X-Knn-Tenant`` header), legacy
  ``/knn`` resolving to the default tenant byte-identically, unknown
  tenants 404ing with the tenant list, per-tenant quota 429 +
  Retry-After, the per-tenant /stats namespace and ``{tenant=}`` metric
  labels — and a single-index server showing NONE of that surface (the
  wire format is unchanged for existing deployments).
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

K = 5


def _tenant_points(i: int, n: int = 240):
    """Each tenant gets its OWN point cloud (different seed and size —
    different sizes exercise the shared pad-shape class)."""
    from tests.oracle import random_points

    return random_points(n + 30 * i, seed=100 + i, scale=0.5)


# ------------------------------------------------------ spec + registry


class TestTenantSpecAndRegistry:
    def test_spec_rejects_url_hostile_names(self):
        from mpi_cuda_largescaleknn_tpu.serve.tenancy import TenantSpec

        with pytest.raises(ValueError, match="bad tenant name"):
            TenantSpec("", points=np.zeros((4, 3)))
        with pytest.raises(ValueError, match="bad tenant name"):
            TenantSpec("a/b", points=np.zeros((4, 3)))

    def test_registry_roundtrip_and_sorted_names(self):
        from mpi_cuda_largescaleknn_tpu.serve.tenancy import TenantRegistry

        reg = TenantRegistry()
        reg.add("zeta", "engine-z")
        reg.add("alpha", "engine-a")
        assert reg.get("alpha") == "engine-a"
        assert reg.names() == ["alpha", "zeta"]  # sorted, not insertion
        assert "zeta" in reg and "nope" not in reg
        assert len(reg) == 2

    def test_unknown_tenant_raises_keyerror_subclass(self):
        from mpi_cuda_largescaleknn_tpu.serve.tenancy import (
            TenantRegistry,
            UnknownTenantError,
        )

        reg = TenantRegistry()
        with pytest.raises(UnknownTenantError):
            reg.get("stranger")
        assert issubclass(UnknownTenantError, KeyError)


# -------------------------------------------------------------- quotas


class TestTenantQuotas:
    def _quotas(self, global_rows=100, **kw):
        from mpi_cuda_largescaleknn_tpu.serve.admission import (
            AdmissionController,
        )
        from mpi_cuda_largescaleknn_tpu.serve.tenancy import TenantQuotas

        ctrl = AdmissionController(max_queue_rows=global_rows)
        return ctrl, TenantQuotas(ctrl, **kw)

    def test_over_quota_rejects_with_retry_after(self):
        ctrl, q = self._quotas(quotas={"a": 10}, retry_after_s=0.25)
        q.admit("a", 8)
        with pytest.raises(Exception, match="over quota") as e:
            q.admit("a", 8)  # 8 + 8 > 10
        assert e.value.retry_after_s == pytest.approx(0.25)
        assert q.stats()["tenants"]["a"]["rejected"] == 1
        # the reservation that DID land is still held and releasable
        assert q.stats()["tenants"]["a"]["inflight_rows"] == 8
        q.release("a", 8)
        assert q.stats()["tenants"]["a"]["inflight_rows"] == 0

    def test_zero_quota_means_unsliced_global_cap_only(self):
        ctrl, q = self._quotas(global_rows=20)
        q.admit("free", 20)  # quota 0 -> only the global cap applies
        from mpi_cuda_largescaleknn_tpu.serve.admission import OverloadError

        with pytest.raises(OverloadError, match="queue full"):
            q.admit("free", 1)
        q.release("free", 20)

    def test_global_reject_rolls_back_tenant_reservation(self):
        ctrl, q = self._quotas(global_rows=10, quotas={"a": 50})
        ctrl.admit(8)  # someone else holds most of the global budget
        from mpi_cuda_largescaleknn_tpu.serve.admission import OverloadError

        with pytest.raises(OverloadError):
            q.admit("a", 8)  # under tenant quota, over GLOBAL cap
        # the tenant slice was rolled back — a smaller request still fits
        assert q.stats()["tenants"]["a"]["inflight_rows"] == 0
        q.admit("a", 2)
        q.release("a", 2)
        ctrl.release(8)

    def test_one_tenant_cannot_starve_another(self):
        ctrl, q = self._quotas(global_rows=100, default_quota_rows=60)
        q.admit("hog", 60)
        with pytest.raises(Exception, match="over quota"):
            q.admit("hog", 1)
        q.admit("quiet", 40)  # the hog left room for everyone else
        q.release("hog", 60)
        q.release("quiet", 40)

    def test_set_quota_and_context_manager(self):
        ctrl, q = self._quotas()
        q.set_quota("a", 5)
        assert q.quota("a") == 5 and q.quota("b") == 0
        with q.admitted_rows("a", 5):
            assert q.stats()["tenants"]["a"]["inflight_rows"] == 5
        assert q.stats()["tenants"]["a"]["inflight_rows"] == 0
        assert ctrl.inflight_rows() == 0


# ----------------------------------------------- shared pool (fake engines)


class _FakeEngine:
    def __init__(self, key, rows, device_bytes):
        self.key = key
        self.host_points = rows
        self.device_bytes = device_bytes


class _TenantPoolRig:
    """A multi-tenant SlabPool over fakes: two registered tenants,
    injectable counter clock, per-(tenant, slab) build log."""

    def __init__(self, slab_bytes=100, build_cost=0.5, **pool_kw):
        from mpi_cuda_largescaleknn_tpu.serve.slabpool import (
            SlabPool,
            SlabSource,
        )

        self.now = [0.0]
        self.built = []
        self.pool = SlabPool(clock=lambda: self.now[0], **pool_kw)

        def mk_factory(tenant):
            def factory(slab, rows, begin):
                self.now[0] += build_cost
                self.built.append((tenant, slab))
                return _FakeEngine((tenant, slab), rows, slab_bytes)
            return factory

        for i, tenant in enumerate(("hot", "cold")):
            n = 40 + 8 * i
            src = SlabSource(points=np.arange(n * 3, dtype=np.float32)
                             .reshape(n, 3), num_slabs=4)
            self.pool.register(tenant, src, mk_factory(tenant))
            setattr(self, f"{tenant}_src", src)


class TestSharedPoolTenancy:
    def test_tuple_keys_route_to_each_tenants_source(self):
        rig = _TenantPoolRig()
        e_hot = rig.pool.ensure(("hot", 0))
        e_cold = rig.pool.ensure(("cold", 0))
        assert e_hot.key == ("hot", 0) and e_cold.key == ("cold", 0)
        # same local slab id, DIFFERENT rows: each tenant's own index
        assert (e_hot.host_points.tobytes()
                == rig.hot_src.read(0).tobytes())
        assert (e_cold.host_points.tobytes()
                == rig.cold_src.read(0).tobytes())
        assert rig.built == [("hot", 0), ("cold", 0)]
        rig.pool.close()

    def test_per_tenant_accounting_in_stats(self):
        rig = _TenantPoolRig(device_budget_bytes=200)  # 2 slabs
        p = rig.pool
        p.ensure(("hot", 0))
        p.ensure(("hot", 0))           # device hit for "hot"
        p.ensure(("cold", 0))
        p.ensure(("cold", 1))          # evicts hot/0 (LRU)
        s = p.stats()
        assert s["num_slabs"] == 8     # 4 + 4 across both sources
        t = s["tenants"]
        assert t["hot"]["promotions"] == 1 and t["hot"]["device_hits"] == 1
        assert t["hot"]["evictions"] == 1 and t["hot"]["device_resident"] == 0
        assert t["cold"]["promotions"] == 2 and t["cold"]["evictions"] == 0
        assert t["cold"]["device_resident"] == 2
        # stall seconds split per tenant and sum to the pool totals
        stalls, secs = p.stall_totals()
        h = p.stall_totals(tenant="hot")
        c = p.stall_totals(tenant="cold")
        assert h[0] + c[0] == stalls
        assert h[1] + c[1] == pytest.approx(secs)
        p.close()

    def test_eviction_fairness_hot_pages_survive_cold_churn(self):
        """The fairness contract under skew: a tenant whose pages are
        re-touched keeps them resident; an idle tenant's churn only
        cycles the remaining budget (LRU is tenant-blind — recency is
        the only currency, so activity IS the fair share)."""
        rig = _TenantPoolRig(device_budget_bytes=300)  # 3 slabs
        p = rig.pool
        p.ensure(("hot", 0))
        for slab in (0, 1, 2, 3, 0, 1, 2, 3):  # cold churns its index
            rig.now[0] += 1.0
            p.ensure(("cold", slab))
            rig.now[0] += 1.0
            p.ensure(("hot", 0))  # hot re-touches its one page
        assert ("hot", 0) in p.resident_slabs()  # never evicted
        t = p.stats()["tenants"]
        assert t["hot"]["promotions"] == 1 and t["hot"]["evictions"] == 0
        assert t["cold"]["evictions"] >= 4  # churn stayed in cold's share
        p.close()

    def test_pins_and_prefetch_use_tuple_keys(self):
        rig = _TenantPoolRig(device_budget_bytes=100)  # 1 slab
        p = rig.pool
        p.pin([("hot", 2)])
        p.ensure(("hot", 2))
        p.ensure(("cold", 3))  # pinned hot page overcommits, not evicts
        assert ("hot", 2) in p.resident_slabs()
        assert p.stats()["overcommits"] == 1
        p.unpin([("hot", 2)])
        p.prefetch([("cold", 1)])
        assert p.wait_idle(timeout_s=10)
        assert p.stats()["tenants"]["cold"]["prefetch_enqueued"] == 1
        p.close()


# ------------------------------------------- multi-tenant engine (real jax)


@pytest.fixture(scope="module")
def tenancy_rig():
    """Three tenants behind one shared pool + AOT cache, and an isolated
    single-tenant twin per tenant over identical points — the parity
    references. Both sides canonical: tiled engine, device merge."""
    from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
    from mpi_cuda_largescaleknn_tpu.serve.slabpool import StreamingKnnEngine
    from mpi_cuda_largescaleknn_tpu.serve.tenancy import (
        MultiTenantEngine,
        TenantSpec,
    )

    kw = dict(engine="tiled", bucket_size=64, max_batch=32, min_batch=16,
              merge="device")
    names = ["t0", "t1", "t2"]
    points = {n: _tenant_points(i) for i, n in enumerate(names)}
    mesh = get_mesh(2)
    shared = MultiTenantEngine(
        [TenantSpec(n, points=points[n], num_slabs=3) for n in names],
        k=K, mesh=mesh, prefetch_depth=0, **kw)
    warm = shared.warmup()
    twins = {}
    for n in names:
        twins[n] = StreamingKnnEngine(points=points[n], num_slabs=3, k=K,
                                      mesh=mesh, prefetch_depth=0, **kw)
        twins[n].warmup()
    yield names, points, shared, warm, twins
    for t in twins.values():
        t.close()
    shared.close()


def _probes(pts, seed):
    rng = np.random.default_rng(seed)
    return [rng.random((9, 3)).astype(np.float32),
            pts[:1], pts[31:48]]


class TestMultiTenantEngine:
    def test_per_tenant_bitwise_parity_across_budgets(self, tenancy_rig):
        """THE acceptance bar: every tenant's answers through the shared
        pool equal its isolated twin's bytes at budgets {1 slab, half,
        everything} — dists AND neighbor ids."""
        names, points, shared, _warm, twins = tenancy_rig
        slab_b = shared.slab_device_bytes
        for budget_slabs in (1, 4, 0):  # 0 = unlimited
            shared.slab_pool.set_device_budget(slab_b * budget_slabs)
            for i, n in enumerate(names):
                for q in _probes(points[n], seed=7 + i):
                    dt, nt = twins[n].query(q)
                    ds, ns = shared.query(q, tenant=n)
                    assert np.array_equal(dt, ds), \
                        f"dists diverge for {n} at budget {budget_slabs}"
                    assert np.array_equal(nt, ns), \
                        f"ids diverge for {n} at budget {budget_slabs}"
        shared.slab_pool.set_device_budget(0)

    def test_compile_count_flat_across_tenants(self, tenancy_rig):
        """Tenant count never becomes compile count: warming THREE
        tenants through the shared cache costs no more compiles than one
        isolated single-tenant engine, and serving all of them after
        warmup adds zero."""
        names, points, shared, warm, twins = tenancy_rig
        single = twins[names[0]].stats()["compile_count"]
        assert 0 < warm["compile_count"] <= single
        before = shared.stats()["compile_count"]
        for n in names:
            shared.query(points[n][:5], tenant=n)
        assert shared.stats()["compile_count"] == before

    def test_resolve_and_unknown_tenant(self, tenancy_rig):
        from mpi_cuda_largescaleknn_tpu.serve.tenancy import (
            UnknownTenantError,
        )

        names, points, shared, _warm, _twins = tenancy_rig
        assert shared.default_tenant == names[0]
        name, eng = shared.resolve(None)  # legacy /knn route
        assert name == names[0] and eng.n_points == len(points[names[0]])
        with pytest.raises(UnknownTenantError):
            shared.resolve("stranger")
        with pytest.raises(UnknownTenantError):
            shared.query(points[names[0]][:2], tenant="stranger")

    def test_dispatch_handle_carries_tenant_namespace(self, tenancy_rig):
        names, points, shared, _warm, twins = tenancy_rig
        q = points[names[2]][:4]
        h = shared.dispatch(q, tenant=names[2])
        assert h.tenant == names[2] and h.n == 4
        ds, _ns = shared.complete(h)
        dt, _nt = twins[names[2]].query(q)
        assert np.array_equal(dt, ds)

    def test_stats_carry_per_tenant_namespace(self, tenancy_rig):
        names, points, shared, _warm, _twins = tenancy_rig
        s = shared.stats()
        assert s["n_points"] == sum(len(points[n]) for n in names)
        assert s["default_tenant"] == names[0]
        assert sorted(s["tenants"]) == sorted(names)
        for n in names:
            t = s["tenants"][n]
            assert t["n_points"] == len(points[n])
            assert t["num_slabs"] == 3 and t["k"] == K


# ------------------------------------------------------------ HTTP surface


def _url(server):
    return f"http://127.0.0.1:{server.server_address[1]}"


def _post(base, payload: dict, path="/knn", headers=(), timeout=60):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(),
        headers=dict({"Content-Type": "application/json"}, **dict(headers)))
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _get(base, path, timeout=30):
    with urllib.request.urlopen(base + path, timeout=timeout) as resp:
        body = resp.read()
    try:
        return json.loads(body)
    except ValueError:
        return body.decode()


@pytest.fixture(scope="module")
def mt_server(tenancy_rig):
    from mpi_cuda_largescaleknn_tpu.serve.server import build_server

    _names, _points, shared, _warm, _twins = tenancy_rig
    srv = build_server(shared, port=0, max_delay_s=0.002)
    srv.ready = True
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield srv
    srv.close()


@pytest.fixture(scope="module")
def single_server(tenancy_rig):
    """A single-index server over one of the twins — the wire-format
    control: no tenant surface may appear."""
    from mpi_cuda_largescaleknn_tpu.serve.server import build_server

    names, _points, _shared, _warm, twins = tenancy_rig
    srv = build_server(twins[names[0]], port=0, max_delay_s=0.002)
    srv.ready = True
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield srv
    srv.close()


class TestTenancyHTTP:
    def test_v1_route_serves_that_tenants_index(self, tenancy_rig,
                                                mt_server):
        names, points, _shared, _warm, twins = tenancy_rig
        base = _url(mt_server)
        for n in names:
            q = points[n][:6]
            status, resp = _post(base, {"queries": q.tolist(),
                                        "neighbors": True},
                                 path=f"/v1/{n}/knn")
            dt, nt = twins[n].query(q)
            assert status == 200
            assert np.array_equal(np.asarray(resp["dists"], np.float32),
                                  np.asarray(dt, np.float32))
            assert np.array_equal(np.asarray(resp["neighbors"]),
                                  np.asarray(nt))

    def test_legacy_route_is_the_default_tenant_bytes(self, tenancy_rig,
                                                      mt_server):
        names, points, _shared, _warm, _twins = tenancy_rig
        base = _url(mt_server)
        q = points[names[0]][:5].tolist()
        _s, legacy = _post(base, {"queries": q, "neighbors": True})
        _s, explicit = _post(base, {"queries": q, "neighbors": True},
                             path=f"/v1/{names[0]}/knn")
        assert legacy["dists"] == explicit["dists"]
        assert legacy["neighbors"] == explicit["neighbors"]

    def test_header_and_json_field_route_like_the_url(self, tenancy_rig,
                                                      mt_server):
        names, points, _shared, _warm, _twins = tenancy_rig
        base = _url(mt_server)
        n = names[2]
        q = points[n][:4].tolist()
        _s, via_url = _post(base, {"queries": q, "neighbors": True},
                            path=f"/v1/{n}/knn")
        _s, via_field = _post(base, {"queries": q, "neighbors": True,
                                     "tenant": n})
        _s, via_header = _post(base, {"queries": q, "neighbors": True},
                               headers={"X-Knn-Tenant": n})
        assert via_field["dists"] == via_url["dists"]
        assert via_header["dists"] == via_url["dists"]
        assert via_field["neighbors"] == via_url["neighbors"]

    def test_unknown_tenant_404_lists_tenants(self, tenancy_rig,
                                              mt_server):
        names, points, _shared, _warm, _twins = tenancy_rig
        base = _url(mt_server)
        q = points[names[0]][:2].tolist()
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(base, {"queries": q}, path="/v1/stranger/knn")
        assert e.value.code == 404
        body = json.loads(e.value.read())
        assert "no such tenant" in body["error"]
        assert body["tenants"] == sorted(names)

    def test_quota_429_with_retry_after(self, tenancy_rig, mt_server):
        names, points, _shared, _warm, _twins = tenancy_rig
        base = _url(mt_server)
        n = names[1]
        mt_server.quotas.set_quota(n, 3)
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(base, {"queries": points[n][:8].tolist()},
                      path=f"/v1/{n}/knn")
            assert e.value.code == 429
            assert float(e.value.headers["Retry-After"]) > 0
            assert "over quota" in json.loads(e.value.read())["error"]
            # other tenants are untouched by n's quota
            status, _ = _post(base, {"queries": points[names[0]][:8]
                                     .tolist()},
                              path=f"/v1/{names[0]}/knn")
            assert status == 200
            # and n itself still serves requests under its cap
            status, _ = _post(base, {"queries": points[n][:3].tolist()},
                              path=f"/v1/{n}/knn")
            assert status == 200
        finally:
            mt_server.quotas.set_quota(n, 0)
        st = _get(base, f"/v1/{n}/stats")
        assert st["quota"]["rejected"] >= 1

    def test_stats_has_per_tenant_namespace(self, tenancy_rig, mt_server):
        names, _points, _shared, _warm, _twins = tenancy_rig
        stats = _get(_url(mt_server), "/stats")
        assert sorted(stats["tenants"]) == sorted(names)
        for n in names:
            block = stats["tenants"][n]
            assert set(block) >= {"server", "quota", "engine"}
            assert "request_latency" in block["server"]
            assert set(block["quota"]) >= {"quota_rows", "inflight_rows",
                                           "rejected"}

    def test_per_tenant_stats_route(self, tenancy_rig, mt_server):
        names, points, _shared, _warm, _twins = tenancy_rig
        base = _url(mt_server)
        st = _get(base, f"/v1/{names[1]}/stats")
        assert st["tenant"] == names[1]
        assert st["engine"]["n_points"] == len(points[names[1]])
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(base, "/v1/stranger/stats")
        assert e.value.code == 404
        assert json.loads(e.value.read())["tenants"] == sorted(names)

    def test_metrics_carry_tenant_labels(self, tenancy_rig, mt_server):
        names, points, _shared, _warm, _twins = tenancy_rig
        base = _url(mt_server)
        for n in names:  # every tenant has served at least one request
            _post(base, {"queries": points[n][:2].tolist()},
                  path=f"/v1/{n}/knn")
        m = _get(base, "/metrics")
        for n in names:
            assert f'knn_requests_total{{tenant="{n}"}}' in m
            assert f'knn_slab_pool_tenant_resident{{tenant="{n}"' in m
            assert f'knn_tenant_quota_rows{{tenant="{n}"}}' in m
        assert 'knn_slab_tenant_promotions_total{tenant="' in m
        # the unlabeled aggregates still lead each family
        assert "\nknn_requests_total " in "\n" + m

    def test_single_index_server_shows_no_tenant_surface(self,
                                                         tenancy_rig,
                                                         single_server):
        names, points, _shared, _warm, _twins = tenancy_rig
        base = _url(single_server)
        status, _resp = _post(base, {"queries": points[names[0]][:3]
                                     .tolist()})
        assert status == 200
        # tenancy URLs are strangers here — no accidental namespace
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(base, {"queries": points[names[0]][:3].tolist()},
                  path=f"/v1/{names[0]}/knn")
        assert e.value.code == 404
        assert "tenants" not in _get(base, "/stats")
        assert single_server.quotas is None
        assert '{tenant="' not in _get(base, "/metrics")
