"""Recall-SLO approximate tier (serve/recall.py + its plumbing).

Three layers under test: the plan/policy values themselves (pure units),
the engine's plan-keyed approximate programs (measured recall against
the exact oracle on a fixture large enough that the knobs demonstrably
engage), and the serving stack's contract — plan-keyed sub-batching in
the batcher, the ``exact``/``X-Knn-*`` response surface, the /stats and
/metrics accounting, and the streaming engine's skip-cold trade. The
exact default path staying bitwise-unchanged is asserted at every layer
it could drift: it is the tier's founding promise.
"""

import json
import threading
import time
import urllib.error
import urllib.request
from dataclasses import replace

import numpy as np
import pytest

from mpi_cuda_largescaleknn_tpu.serve.batcher import DynamicBatcher
from mpi_cuda_largescaleknn_tpu.serve.recall import (
    DEFAULT_PLANS,
    EXACT_PLAN,
    RecallPlan,
    RecallPolicy,
    measured_recall,
)
from tools.recall_harness import workload_queries

K = 8


# ----------------------------------------------------------------- plan units


class TestRecallPlan:
    def test_exact_plan_is_exact(self):
        assert EXACT_PLAN.is_exact
        assert RecallPlan().is_exact

    def test_default_plans_are_approximate_and_cheapest_first(self):
        assert all(not p.is_exact for p in DEFAULT_PLANS)
        ests = [p.recall_estimated for p in DEFAULT_PLANS]
        assert ests == sorted(ests)

    @pytest.mark.parametrize("bad", [
        {"prune_shrink": 0.0}, {"prune_shrink": 1.5},
        {"visit_frac": 0.0}, {"visit_frac": -0.1},
        {"route_slack": 1.0}, {"route_slack": -0.01},
        {"recall_estimated": 0.0}, {"recall_estimated": 1.2},
    ])
    def test_knob_validation(self, bad):
        with pytest.raises(ValueError):
            RecallPlan(**bad)

    def test_keys_exclude_recall_target(self):
        """Two requests on one plan at different targets must share both
        the compiled program and the batch — targets are response
        metadata, not execution knobs."""
        plan = DEFAULT_PLANS[1]
        retargeted = replace(plan, recall_target=0.87)
        assert retargeted.program_key() == plan.program_key()
        assert retargeted.batch_key() == plan.batch_key()

    def test_batch_key_refines_program_key(self):
        """Dispatch-time knobs (route_slack, stream_skip_cold) split
        batches but not executables."""
        plan = DEFAULT_PLANS[0]
        slacked = replace(plan, route_slack=0.0, stream_skip_cold=False)
        assert slacked.program_key() == plan.program_key()
        assert slacked.batch_key() != plan.batch_key()

    def test_json_roundtrip_ignores_unknown_keys(self):
        plan = DEFAULT_PLANS[2]
        obj = plan.to_json()
        assert RecallPlan.from_json(obj) == plan
        obj["future_knob"] = 42  # forward compat: old servers, new tables
        assert RecallPlan.from_json(obj) == plan


# --------------------------------------------------------------- policy units


class TestRecallPolicy:
    def test_rejects_exact_plan_in_table(self):
        with pytest.raises(ValueError, match="exact"):
            RecallPolicy((EXACT_PLAN,))

    def test_rejects_out_of_order_plans(self):
        with pytest.raises(ValueError, match="cheapest"):
            RecallPolicy(tuple(reversed(DEFAULT_PLANS)))

    def test_no_target_and_full_target_are_the_exact_tier(self):
        policy = RecallPolicy()
        assert policy.plan_for(None) is None
        assert policy.plan_for(1.0) is None
        assert policy.stats()["selected"] == {"exact": 2}

    def test_selects_cheapest_plan_meeting_target(self):
        policy = RecallPolicy()
        assert policy.plan_for(0.5).name == "approx-fast"
        assert policy.plan_for(0.85).name == "approx-fast"
        assert policy.plan_for(0.9).name == "approx-balanced"
        assert policy.plan_for(0.99).name == "approx-near"
        # a target above every calibrated claim is unmeetable -> exact
        assert policy.plan_for(0.995) is None

    @pytest.mark.parametrize("bad", [0.0, -0.5, 1.5])
    def test_invalid_target_raises(self, bad):
        with pytest.raises(ValueError):
            RecallPolicy().plan_for(bad)

    def test_selection_returns_a_targeted_copy(self):
        """plan_for hands back a copy carrying the request's target; the
        table entry (shared across threads) must never mutate."""
        policy = RecallPolicy()
        got = policy.plan_for(0.9)
        assert got.recall_target == 0.9
        assert policy.plans[1].recall_target == 1.0
        assert got.batch_key() == policy.plans[1].batch_key()

    def test_stats_counts_per_plan(self):
        policy = RecallPolicy()
        for t in (0.5, 0.9, 0.9, None, 1.0):
            policy.plan_for(t)
        sel = policy.stats()["selected"]
        assert sel == {"approx-fast": 1, "approx-balanced": 2, "exact": 2}

    def test_from_dict_resorts_cheapest_first(self):
        obj = {"plans": [p.to_json() for p in reversed(DEFAULT_PLANS)]}
        policy = RecallPolicy.from_dict(obj)
        assert [p.name for p in policy.plans] == [
            "approx-fast", "approx-balanced", "approx-near"]


class TestMeasuredRecall:
    def test_identical_ids_are_recall_one(self):
        idx = np.arange(12, dtype=np.int32).reshape(3, 4)
        assert measured_recall(idx, idx) == 1.0

    def test_disjoint_ids_are_recall_zero(self):
        e = np.arange(8, dtype=np.int32).reshape(2, 4)
        assert measured_recall(e + 100, e) == 0.0

    def test_partial_overlap_and_pad_ids(self):
        exact = np.array([[0, 1, 2, 3]], np.int32)
        approx = np.array([[0, 1, -1, -1]], np.int32)  # -1 pads never hit
        assert measured_recall(approx, exact) == 0.5

    def test_shape_mismatch_raises(self):
        with pytest.raises(ValueError, match="shape"):
            measured_recall(np.zeros((2, 4)), np.zeros((2, 5)))


# ------------------------------------------------------- engine-tier recall


@pytest.fixture(scope="module")
def big_engine():
    """A fixture large enough that the approximate knobs demonstrably
    engage (on the 1500-point serving fixture every plan still measures
    recall 1.0 — too small to skip anything): 16384 uniform points,
    one 128-wide shape bucket, bucket_size 64."""
    from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
    from mpi_cuda_largescaleknn_tpu.serve.engine import ResidentKnnEngine

    rng = np.random.default_rng(7)
    pts = rng.random((16384, 3)).astype(np.float32)
    eng = ResidentKnnEngine(pts, K, mesh=get_mesh(8), engine="tiled",
                            bucket_size=64, max_batch=128, min_batch=128)
    eng.warmup()
    return eng


def _chunked_ids(engine, q, plan=None):
    outs = [np.asarray(engine.query(q[i:i + 128], plan=plan)[1])
            for i in range(0, len(q), 128)]
    return np.concatenate(outs, axis=0)


class TestEngineRecallTier:
    def test_measured_recall_meets_every_calibrated_claim(self, big_engine):
        """THE tier's honesty bar, at the engine layer: each built-in
        plan's measured recall on the harness workload shapes must meet
        its calibrated claim — and approx-fast must measure BELOW 1.0
        somewhere, proving the knobs actually skipped work (a fixture
        where every plan is accidentally exact would gate nothing)."""
        engaged = False
        for wl in ("uniform", "clustered"):
            q = workload_queries(wl, 256, seed=0)
            exact = _chunked_ids(big_engine, q)
            for plan in DEFAULT_PLANS:
                r = measured_recall(_chunked_ids(big_engine, q, plan=plan),
                                    exact)
                assert r >= plan.recall_estimated, \
                    f"{plan.name} on {wl}: measured {r:.4f} < claimed " \
                    f"{plan.recall_estimated}"
                engaged = engaged or r < 1.0
        assert engaged, "no plan dropped a single neighbor — fixture " \
                        "too small to exercise the approximate tier"

    def test_plan_keyed_executables_compile_once(self, big_engine):
        """Each distinct program_key compiles its own AOT executable
        exactly once; reuse (same plan, same width) never retraces, and
        two plans can never collide on one executable."""
        q = workload_queries("uniform", 128, seed=1)
        plan = DEFAULT_PLANS[1]
        before = big_engine.compile_count
        big_engine.query(q, plan=plan)
        first = big_engine.compile_count
        assert first >= before  # may be warm from the recall sweep above
        big_engine.query(q, plan=plan)
        assert big_engine.compile_count == first
        other = DEFAULT_PLANS[0]
        assert other.program_key() != plan.program_key()
        big_engine.query(q, plan=other)
        big_engine.query(q, plan=other)
        assert big_engine.compile_count >= first  # distinct key, own exe

    def test_exact_path_bitwise_unchanged_after_approx_traffic(
            self, big_engine):
        q = workload_queries("sweep", 128, seed=2)
        d0, i0 = (np.asarray(a) for a in big_engine.query(q))
        for plan in DEFAULT_PLANS:
            big_engine.query(q, plan=plan)
        d1, i1 = (np.asarray(a) for a in big_engine.query(q))
        assert np.array_equal(d0, d1) and np.array_equal(i0, i1)


# ------------------------------------------------------ batcher sub-batching


class _PlanRecordingFn:
    """Batcher test double: records (rows, plan) per engine call and
    echoes each query row's first coordinate so submitters can verify
    they got THEIR rows back after demux."""

    def __init__(self):
        self.calls = []
        self._lock = threading.Lock()

    def __call__(self, queries, plan=None):
        with self._lock:
            self.calls.append((len(queries), plan))
        time.sleep(0.005)  # hold the worker so the queue builds depth
        d = np.asarray(queries)[:, 0].astype(np.float32)
        nbrs = np.zeros((len(queries), K), np.int32)
        return d, nbrs


class TestBatcherMixedSlo:
    def test_mixed_slo_traffic_splits_into_per_plan_batches(self):
        """Concurrent exact + two-plan traffic: every executed engine
        batch carries exactly one plan (the batcher never coalesces
        across batch_key), and each submitter's rows come back intact."""
        fn = _PlanRecordingFn()
        b = DynamicBatcher(fn, max_batch=64, max_delay_s=0.02)
        plans = [None, DEFAULT_PLANS[0], DEFAULT_PLANS[2]]
        results = {}

        def client(i):
            q = np.full((3, 3), float(i), np.float32)
            results[i] = b.submit(q, timeout_s=30.0, plan=plans[i % 3])

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        b.shutdown()
        assert len(results) == 12
        for i, (d, nbrs) in results.items():
            np.testing.assert_array_equal(d, np.full(3, float(i)))
            assert nbrs.shape == (3, K)
        # every batch single-plan, and the split actually happened:
        # 12 requests over 3 incompatible keys cannot fit one batch
        assert sum(rows for rows, _ in fn.calls) == 36
        assert len(fn.calls) >= 3
        with b._cond:
            assert b.rows_served == 36
            assert b.rows_served_approx == sum(
                rows for rows, plan in fn.calls if plan is not None)

    def test_same_plan_different_targets_share_a_batch_key(self):
        """recall_target is response metadata: two requests resolved to
        the same plan at different targets are coalescible."""
        a = replace(DEFAULT_PLANS[1], recall_target=0.9)
        b = replace(DEFAULT_PLANS[1], recall_target=0.95)
        assert a.batch_key() == b.batch_key()


# ---------------------------------------------------------- server contract


@pytest.fixture(scope="module")
def serve_rig():
    """1500-point serving fixture (the test_serve.py geometry) with the
    built-in recall policy: small enough to be fast, and on it every
    approximate plan measures recall 1.0 — which makes BITWISE
    comparisons against the exact engine meaningful for the contract
    tests without a second giant index."""
    from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
    from mpi_cuda_largescaleknn_tpu.serve.engine import ResidentKnnEngine
    from mpi_cuda_largescaleknn_tpu.serve.server import build_server
    from tests.oracle import random_points

    pts = random_points(1500, seed=7)
    eng = ResidentKnnEngine(pts, K, mesh=get_mesh(8), engine="tiled",
                            bucket_size=32, max_batch=128, min_batch=16)
    eng.warmup()
    srv = build_server(eng, port=0, max_delay_s=0.002)
    srv.ready = True
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield eng, srv
    srv.close()


def _base(srv):
    return f"http://127.0.0.1:{srv.server_address[1]}"


def _post(base, payload, timeout=60):
    req = urllib.request.Request(
        base + "/knn", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _get(base, path, timeout=30):
    with urllib.request.urlopen(base + path, timeout=timeout) as resp:
        return json.loads(resp.read()) if path == "/stats" \
            else resp.read().decode()


class TestServerRecallContract:
    def test_no_recall_field_is_bitwise_exact_and_wire_unchanged(
            self, serve_rig):
        """The founding promise: a request without a recall field takes
        the pre-tier path — engine-bitwise dists, and NO new response
        fields — even after approximate traffic has run on the server."""
        eng, srv = serve_rig
        q = workload_queries("uniform", 24, seed=5)
        want = np.asarray(eng.query(q)[0], np.float64)
        for _round in range(2):
            st, out = _post(_base(srv), {"queries": q.tolist()})
            assert st == 200
            assert np.array_equal(np.asarray(out["dists"]), want)
            for field in ("exact", "recall_target", "recall_estimated",
                          "recall_plan"):
                assert field not in out
            # interleave approx traffic, then re-check the exact wire
            _post(_base(srv), {"queries": q.tolist(), "recall": 0.9})

    def test_full_target_served_exactly(self, serve_rig):
        eng, srv = serve_rig
        q = workload_queries("uniform", 8, seed=6)
        st, out = _post(_base(srv), {"queries": q.tolist(), "recall": 1.0})
        assert st == 200
        assert out["exact"] is True
        assert out["recall_target"] == 1.0
        assert out["recall_estimated"] == 1.0
        assert "recall_plan" not in out
        assert np.array_equal(np.asarray(out["dists"]),
                              np.asarray(eng.query(q)[0], np.float64))

    def test_unmeetable_target_falls_back_to_exact(self, serve_rig):
        _eng, srv = serve_rig
        q = workload_queries("uniform", 4, seed=6)
        st, out = _post(_base(srv), {"queries": q.tolist(), "recall": 0.995})
        assert st == 200
        assert out["exact"] is True and out["recall_estimated"] == 1.0
        assert out["recall_target"] == 0.995

    def test_approx_response_contract(self, serve_rig):
        _eng, srv = serve_rig
        q = workload_queries("clustered", 8, seed=6)
        st, out = _post(_base(srv), {"queries": q.tolist(), "recall": 0.9,
                                     "neighbors": True})
        assert st == 200
        assert out["exact"] is False
        assert out["recall_plan"] == "approx-balanced"
        assert out["recall_target"] == 0.9
        assert out["recall_estimated"] == 0.95
        assert len(out["neighbors"]) == len(q)
        assert all(len(row) == K for row in out["neighbors"])

    @pytest.mark.parametrize("bad", [0.0, -0.2, 1.5])
    def test_invalid_recall_target_is_400(self, serve_rig, bad):
        _eng, srv = serve_rig
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(_base(srv), {"queries": [[0.5, 0.5, 0.5]], "recall": bad})
        assert err.value.code == 400

    def test_binary_codec_carries_recall_headers(self, serve_rig):
        eng, srv = serve_rig
        q = workload_queries("uniform", 6, seed=8)
        req = urllib.request.Request(
            _base(srv) + "/knn?recall=0.9", data=q.tobytes(),
            headers={"Content-Type": "application/octet-stream"})
        with urllib.request.urlopen(req, timeout=60) as resp:
            assert resp.status == 200
            assert resp.headers["X-Knn-Exact"] == "0"
            assert resp.headers["X-Knn-Recall-Plan"] == "approx-balanced"
            assert resp.headers["X-Knn-Recall-Target"] == "0.9"
            assert resp.headers["X-Knn-Recall-Estimated"] == "0.95"
            body = np.frombuffer(resp.read(), "<f4")
        assert body.shape == (len(q),)
        # no recall option -> the pre-tier binary wire, headers absent
        req = urllib.request.Request(
            _base(srv) + "/knn", data=q.tobytes(),
            headers={"Content-Type": "application/octet-stream"})
        with urllib.request.urlopen(req, timeout=60) as resp:
            assert resp.headers["X-Knn-Exact"] is None
            exact_bytes = resp.read()
        assert exact_bytes == np.asarray(eng.query(q)[0],
                                         "<f4").tobytes()

    def test_stats_and_metrics_surface(self, serve_rig):
        _eng, srv = serve_rig
        base = _base(srv)
        q = workload_queries("uniform", 4, seed=9)
        _post(base, {"queries": q.tolist(), "recall": 0.5})
        _post(base, {"queries": q.tolist()})
        stats = _get(base, "/stats")
        rec = stats["recall"]
        assert rec["tiers"]["approx"] >= 1
        assert rec["tiers"]["exact"] >= 1
        hist = rec["estimated_hist"]
        assert len(hist["counts"]) == len(hist["edges"]) + 1
        assert sum(hist["counts"]) == hist["count"] == rec["tiers"]["approx"]
        pol = rec["policy"]
        assert pol["source"] == "builtin"
        assert pol["selected"].get("approx-fast", 0) >= 1
        assert [p["name"] for p in pol["plans"]] == [
            "approx-fast", "approx-balanced", "approx-near"]
        metrics = _get(base, "/metrics")
        assert 'knn_recall_requests_total{tier="approx"}' in metrics
        assert 'knn_recall_requests_total{tier="exact"}' in metrics
        assert "knn_recall_estimated_bucket" in metrics
        assert "knn_recall_estimated_count" in metrics


# ------------------------------------------------------------ streaming tier


@pytest.fixture(scope="module")
def streaming_rig():
    """The test_slabpool.py streaming geometry: 600 points in two
    spatial clusters over 4 slabs, so a tight device budget forces real
    cold-slab decisions."""
    from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
    from mpi_cuda_largescaleknn_tpu.serve.slabpool import StreamingKnnEngine
    from tests.oracle import random_points

    a = random_points(295, seed=41, scale=0.4)
    b = (random_points(300, seed=42, scale=0.4) + np.float32(0.6))
    pts = np.concatenate([a, b[-5:], b]).astype(np.float32)
    stream = StreamingKnnEngine(points=pts, num_slabs=4, k=4,
                                mesh=get_mesh(2), engine="tiled",
                                bucket_size=64, max_batch=32, min_batch=16,
                                merge="device")
    stream.warmup()
    yield pts, stream
    stream.close()


class TestStreamingRecallTier:
    def test_skip_cold_on_a_warm_pool_is_bitwise_exact(self, streaming_rig):
        """stream_skip_cold only ever trades COLD promotions: with every
        wanted slab device-resident (unbounded budget) the plan's
        dispatch knobs are inert and the answer is the exact bytes."""
        _pts, stream = streaming_rig
        stream.slab_pool.set_device_budget(0)  # unbounded
        plan = RecallPlan(name="warm-stream", stream_skip_cold=True,
                          recall_estimated=0.9)
        rng = np.random.default_rng(3)
        q = rng.random((16, 3)).astype(np.float32)
        de, ie = stream.query(q)  # exact pass also warms the slab set
        da, ia = stream.query(q, plan=plan)
        assert np.array_equal(np.asarray(de), np.asarray(da))
        assert np.array_equal(np.asarray(ie), np.asarray(ia))

    def test_tight_budget_skips_promotions_for_recall(self, streaming_rig):
        """At a one-slab budget with traffic hopping between the two
        clusters, the skip-cold plan must (a) give up at least one cold
        promotion (the counted recall sacrifice), (b) still return k real
        candidates per row (each query's nearest slab is always ensured),
        and (c) leave the exact path bitwise intact afterwards."""
        _pts, stream = streaming_rig
        rng = np.random.default_rng(5)
        qa = (rng.random((8, 3)) * 0.4).astype(np.float32)
        qb = (rng.random((8, 3)) * 0.4 + 0.6).astype(np.float32)
        stream.slab_pool.set_device_budget(0)
        exact_ref = {id(q): [np.asarray(x) for x in stream.query(q)]
                     for q in (qa, qb)}
        stream.slab_pool.set_device_budget(stream.slab_device_bytes)
        plan = RecallPlan(name="tight-stream", stream_skip_cold=True,
                          skip_rescore=True, prune_shrink=0.3,
                          visit_frac=0.5, recall_estimated=0.9)
        before = stream.timers.counter("stream_skipped_promotions")
        skipped = 0
        for _round in range(8):
            for q in (qa, qb):
                _d, ids = stream.query(q, plan=plan)
                assert not (np.asarray(ids) < 0).any(), \
                    "approx row lost its must-visit nearest slab"
            skipped = (stream.timers.counter("stream_skipped_promotions")
                       - before)
            if skipped > 0:
                break
        assert skipped > 0, "one-slab budget + cluster-hopping traffic " \
                            "never skipped a cold promotion"
        # the exact tier is untouched by the approximate churn
        stream.slab_pool.set_device_budget(0)
        for q in (qa, qb):
            d, ids = stream.query(q)
            assert np.array_equal(np.asarray(d), exact_ref[id(q)][0])
            assert np.array_equal(np.asarray(ids), exact_ref[id(q)][1])
