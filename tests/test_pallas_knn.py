"""Pallas engine tests — run in interpreter mode on the CPU fixture.

The same `knn_update_pallas` entry runs compiled on a real TPU; interpret mode
checks the exact merge semantics (strict-< entry, radius bound, incremental
adoption) against the oracle and the XLA brute-force twin.
"""

import numpy as np
import pytest

from mpi_cuda_largescaleknn_tpu.core.types import pad_points
from mpi_cuda_largescaleknn_tpu.ops.brute_force import knn_update_bruteforce
from mpi_cuda_largescaleknn_tpu.ops.candidates import (
    extract_final_result,
    init_candidates,
)
from mpi_cuda_largescaleknn_tpu.ops.pallas.knn_bf import knn_update_pallas

from .oracle import assert_dist_equal, kth_nn_dist, random_points


@pytest.mark.parametrize("n,k", [(100, 1), (300, 8), (520, 17)])
def test_matches_oracle_self_query(n, k):
    pts = random_points(n)
    st = knn_update_pallas(init_candidates(n, k), pts, pts,
                           query_tile=64, point_tile=128)
    got = np.array(extract_final_result(st))
    want = kth_nn_dist(pts, pts, k)
    assert_dist_equal(got, want)


def test_matches_xla_twin_distances():
    pts = random_points(400, seed=2)
    q = random_points(130, seed=3)
    k = 9
    pal = knn_update_pallas(init_candidates(130, k), q, pts,
                            query_tile=64, point_tile=128)
    xla = knn_update_bruteforce(init_candidates(130, k), q, pts,
                                query_tile=64, point_tile=64)
    np.testing.assert_allclose(np.array(pal.dist2), np.array(xla.dist2),
                               rtol=1e-6)


def test_k_greater_than_n_gives_inf():
    pts = random_points(5)
    st = knn_update_pallas(init_candidates(5, 8), pts, pts)
    assert np.all(np.isinf(np.array(extract_final_result(st))))


def test_max_radius_bound():
    pts = random_points(260, seed=3)
    k, r = 10, 0.05
    st = knn_update_pallas(init_candidates(260, k, max_radius=r), pts, pts,
                           query_tile=64, point_tile=128)
    got = np.array(extract_final_result(st))
    want = kth_nn_dist(pts, pts, k, max_radius=r)
    assert_dist_equal(got, want)


def test_incremental_rounds_equal_one_shot():
    pts = random_points(384, seed=5)
    q = random_points(96, seed=6)
    k = 7
    one = knn_update_pallas(init_candidates(96, k), q, pts,
                            query_tile=32, point_tile=128)
    st = init_candidates(96, k)
    st = knn_update_pallas(st, q, pts[:150], query_tile=32, point_tile=128)
    st = knn_update_pallas(st, q, pts[150:],
                           point_ids=np.arange(150, 384, dtype=np.int32),
                           query_tile=32, point_tile=128)
    np.testing.assert_array_equal(np.array(one.dist2), np.array(st.dist2))
    np.testing.assert_array_equal(np.array(one.idx), np.array(st.idx))


def test_sentinel_padding_is_inert():
    pts = random_points(100, seed=9)
    padded, _ = pad_points(pts, 160)
    k = 4
    st_pad = knn_update_pallas(init_candidates(100, k), pts, padded,
                               query_tile=32, point_tile=128)
    st_ref = knn_update_pallas(init_candidates(100, k), pts, pts,
                               query_tile=32, point_tile=128)
    np.testing.assert_array_equal(np.array(st_pad.dist2), np.array(st_ref.dist2))


def test_neighbor_ids_are_correct():
    pts = random_points(200, seed=11)
    k = 5
    st = knn_update_pallas(init_candidates(200, k), pts, pts,
                           query_tile=64, point_tile=128)
    d2 = ((pts[:, None, :] - pts[None, :, :]) ** 2).sum(-1)
    want_idx = np.argsort(d2, axis=1, kind="stable")[:, :k]
    want_d = np.sort(d2, axis=1)[:, :k]
    got_d = np.array(st.dist2)
    np.testing.assert_allclose(got_d, want_d, rtol=1e-5, atol=1e-7)
    # ids must point at rows whose distance equals the reported distance
    got_idx = np.array(st.idx)
    rows = np.arange(200)[:, None]
    np.testing.assert_allclose(d2[rows, got_idx], got_d, rtol=1e-5, atol=1e-7)
    del want_idx


def test_neighbor_ids_decode_exactly():
    """Flat-kernel decode of encoded lane positions: every stored (d2, id)
    pair recomputes exactly, including entries adopted in a SECOND call
    (cross-round continuation, where positions from round 2 coexist with
    ids decoded after round 1)."""
    pts = random_points(300, seed=13)
    k = 6
    q = pts[:96]
    st = knn_update_pallas(init_candidates(96, k), q, pts[:150],
                           point_ids=np.arange(150, dtype=np.int32),
                           query_tile=32, point_tile=128)
    st = knn_update_pallas(st, q, pts[150:],
                           point_ids=np.arange(150, 300, dtype=np.int32),
                           query_tile=32, point_tile=128)
    d2 = np.asarray(st.dist2)
    idx = np.asarray(st.idx)
    for row in range(96):
        finite = np.isfinite(d2[row])
        ids_row = idx[row][finite]
        assert np.all(ids_row >= 0), (row, idx[row])
        assert len(np.unique(ids_row)) == len(ids_row), (row, ids_row)
        recomputed = ((q[row] - pts[ids_row]) ** 2).sum(axis=1)
        # tight tolerance, not bit-equality: the kernel's FMA-contracted
        # f32 sum can differ from numpy by 1 ulp; a WRONG id would be off
        # by orders of magnitude on random points
        np.testing.assert_allclose(recomputed.astype(np.float32),
                                   d2[row][finite], rtol=1e-5, atol=1e-9)
