import numpy as np
import pytest

from mpi_cuda_largescaleknn_tpu.cli.prepartitioned_main import main as prepart_main
from mpi_cuda_largescaleknn_tpu.cli.unordered_main import main as unordered_main
from mpi_cuda_largescaleknn_tpu.io.native import native_read_slab, native_write_at
from mpi_cuda_largescaleknn_tpu.io.reader import (
    read_file_portion,
    read_list_of_file_names,
)

from .oracle import assert_dist_equal, kth_nn_dist, random_points


def test_read_file_portion_slab_semantics(tmp_path):
    pts = random_points(101, seed=1)
    path = tmp_path / "pts.float3"
    pts.tofile(path)
    slabs = []
    for r in range(4):
        slab, begin, total = read_file_portion(str(path), r, 4)
        assert total == 101
        assert begin == 101 * r // 4  # the reference's integer slab bounds
        slabs.append(slab)
    np.testing.assert_array_equal(np.concatenate(slabs), pts)


def test_read_list_of_file_names(tmp_path):
    p = tmp_path / "list.txt"
    p.write_text("a.bin\nb.bin\nc.bin")  # no trailing newline
    assert read_list_of_file_names(str(p)) == ["a.bin", "b.bin", "c.bin"]


def test_native_io_roundtrip(tmp_path):
    if not __import__("shutil").which("g++"):
        pytest.skip("no C++ toolchain; numpy fallback covers correctness")
    pts = random_points(64, seed=2)
    path = str(tmp_path / "n.float3")
    pts.tofile(path)
    mid = native_read_slab(path, 16, 32)
    np.testing.assert_array_equal(mid, pts[16:48])
    out_path = str(tmp_path / "w.float")
    native_write_at(out_path, 0, pts[:8])
    native_write_at(out_path, 8 * 12, pts[8:16])
    np.testing.assert_array_equal(
        np.fromfile(out_path, np.float32).reshape(-1, 3), pts[:16])


def test_unordered_cli_end_to_end(tmp_path):
    pts = random_points(300, seed=3)
    in_path = str(tmp_path / "in.float3")
    out_path = str(tmp_path / "out.float")
    pts.tofile(in_path)
    rc = unordered_main([in_path, "-o", out_path, "-k", "4", "--shards", "4",
                         "--query-tile", "64", "--point-tile", "64"])
    assert rc == 0
    got = np.fromfile(out_path, np.float32)
    assert got.shape == (300,)
    assert_dist_equal(got, kth_nn_dist(pts, pts, 4))


def test_prepartitioned_cli_end_to_end(tmp_path):
    parts = [random_points(80, seed=10 + i) for i in range(3)]
    names = []
    for i, p in enumerate(parts):
        f = str(tmp_path / f"part{i}.float3")
        p.tofile(f)
        names.append(f)
    list_path = str(tmp_path / "files.txt")
    with open(list_path, "w") as f:
        f.write("\n".join(names) + "\n")
    prefix = str(tmp_path / "dists")
    rc = prepart_main([list_path, "-k", "5", "-o", prefix,
                       "--query-tile", "64", "--point-tile", "64"])
    assert rc == 0
    allp = np.concatenate(parts)
    for i, p in enumerate(parts):
        got = np.fromfile(f"{prefix}_{i:06d}.float", np.float32)
        assert_dist_equal(got, kth_nn_dist(p, allp, 5))


def test_cli_rejects_missing_k(tmp_path, capsys):
    with pytest.raises(SystemExit) as e:
        unordered_main(["in.float3", "-o", "out.float"])
    assert e.value.code == 1
    assert "no k specified" in capsys.readouterr().err


def test_cli_rejects_unknown_flag(capsys):
    with pytest.raises(SystemExit) as e:
        unordered_main(["-q", "bogus"])
    assert e.value.code == 1
    assert "unknown cmdline arg" in capsys.readouterr().err


def test_cli_radius_flag(tmp_path):
    pts = random_points(150, seed=4)
    in_path = str(tmp_path / "in.float3")
    out_path = str(tmp_path / "out.float")
    pts.tofile(in_path)
    rc = unordered_main([in_path, "-o", out_path, "-k", "10", "-r", "0.05",
                         "--shards", "2", "--query-tile", "64",
                         "--point-tile", "64"])
    assert rc == 0
    got = np.fromfile(out_path, np.float32)
    assert_dist_equal(got, kth_nn_dist(pts, pts, 10, max_radius=0.05))


class TestWriteIndices:
    def test_unordered_write_indices(self, tmp_path):
        rng = np.random.default_rng(3)
        pts = rng.random((300, 3)).astype(np.float32)
        inp = tmp_path / "p.float3"
        pts.tofile(inp)
        out = tmp_path / "d.float"
        idxp = tmp_path / "i.int32"
        unordered_main([str(inp), "-o", str(out), "-k", "4",
                        "--shards", "4", "--write-indices", str(idxp)])
        idx = np.fromfile(idxp, np.int32).reshape(300, 4)
        d = np.fromfile(out, np.float32)
        full = ((pts[:, None, :] - pts[None, :, :]) ** 2).sum(-1)
        rows = np.arange(300)
        # reported ids must realize the reported k-th distance
        np.testing.assert_allclose(
            np.sqrt(full[rows, idx[:, -1]]), d, rtol=1e-6)
        # first neighbor of a point in a self-query is itself
        assert np.array_equal(idx[:, 0], rows)

    def test_prepartitioned_write_indices(self, tmp_path):
        rng = np.random.default_rng(5)
        pts = rng.random((320, 3)).astype(np.float32)
        pts = pts[np.argsort(pts[:, 0], kind="stable")]
        names = []
        for i in range(8):
            f = tmp_path / f"part{i}.float3"
            pts[i * 40:(i + 1) * 40].tofile(f)
            names.append(str(f))
        lst = tmp_path / "files.txt"
        lst.write_text("\n".join(names) + "\n")
        prepart_main([str(lst), "-o", str(tmp_path / "o"), "-k", "3",
                      "--write-indices", str(tmp_path / "i")])
        idx = np.concatenate([
            np.fromfile(tmp_path / f"i_{r:06d}.int32", np.int32).reshape(-1, 3)
            for r in range(8)])
        d = np.concatenate([
            np.fromfile(tmp_path / f"o_{r:06d}.float", np.float32)
            for r in range(8)])
        full = ((pts[:, None, :] - pts[None, :, :]) ** 2).sum(-1)
        rows = np.arange(320)
        np.testing.assert_allclose(
            np.sqrt(full[rows, idx[:, -1]]), d, rtol=1e-6)
        assert np.array_equal(idx[:, 0], rows)  # global ids, self first


class TestSelfcheck:
    def test_selfcheck_passes_on_correct_output(self, tmp_path, capsys):
        rng = np.random.default_rng(7)
        pts = rng.random((400, 3)).astype(np.float32)
        inp = tmp_path / "p.float3"
        pts.tofile(inp)
        unordered_main([str(inp), "-o", str(tmp_path / "d.float"), "-k", "6",
                        "--shards", "4", "--selfcheck", "64"])
        assert "selfcheck OK (64 samples)" in capsys.readouterr().out

    def test_selfcheck_catches_corruption(self):
        from mpi_cuda_largescaleknn_tpu.obs.selfcheck import verify_sample
        rng = np.random.default_rng(9)
        pts = rng.random((300, 3)).astype(np.float32)
        good = kth_nn_dist(pts, pts, 5)
        assert verify_sample(pts, good, 5, 50) == 50
        bad = good.copy()
        bad[123] *= 1.5
        with pytest.raises(AssertionError, match="selfcheck FAILED"):
            # sample everything so index 123 is always covered
            verify_sample(pts, bad, 5, 300)

    def test_selfcheck_radius_and_inf(self):
        from mpi_cuda_largescaleknn_tpu.obs.selfcheck import verify_sample
        rng = np.random.default_rng(11)
        pts = (rng.random((200, 3)) * 4).astype(np.float32)
        r = 0.3
        want = kth_nn_dist(pts, pts, 8, max_radius=r)
        assert verify_sample(pts, want, 8, 200, max_radius=r) == 200


    def test_selfcheck_inf_pattern_mismatch(self):
        from mpi_cuda_largescaleknn_tpu.obs.selfcheck import verify_sample
        rng = np.random.default_rng(13)
        pts = rng.random((50, 3)).astype(np.float32)
        # k > n: every output is inf, and that passes
        want = kth_nn_dist(pts, pts, 60)
        assert np.all(np.isinf(want))
        assert verify_sample(pts, want, 60, 50) == 50
        # a finite value where the exact answer is inf must fail
        bad = want.copy()
        bad[7] = 1.0
        with pytest.raises(AssertionError, match="selfcheck FAILED"):
            verify_sample(pts, bad, 60, 50)


def test_native_read_failure_surfaces(tmp_path, monkeypatch):
    """A native read that runs and fails must raise, not silently fall back
    to numpy (VERDICT r3 weak #7: a short read / corruption would be
    masked)."""
    from mpi_cuda_largescaleknn_tpu.io import native, reader

    pts = random_points(32, seed=3)
    path = tmp_path / "pts.float3"
    pts.tofile(path)

    monkeypatch.setattr(native, "available", lambda: True)

    def short_read(*a, **kw):
        raise IOError("native read returned 7 != 384")

    monkeypatch.setattr(native, "native_read_slab", short_read)
    with pytest.raises(IOError, match="native read"):
        reader.read_file_portion(str(path), 0, 1)

    # no toolchain at all -> numpy fallback still works
    monkeypatch.setattr(native, "available", lambda: False)
    slab, _, total = reader.read_file_portion(str(path), 0, 1)
    assert total == 32
    np.testing.assert_array_equal(slab, pts)


def test_one_call_api():
    """Top-level ``kth_neighbor_distances``: the library form of the
    unordered CLI contract."""
    import mpi_cuda_largescaleknn_tpu as lsk

    pts = random_points(500, seed=33)
    d, idx = lsk.kth_neighbor_distances(pts, 6, num_shards=4,
                                        bucket_size=64,
                                        return_neighbors=True)
    assert_dist_equal(d, kth_nn_dist(pts, pts, 6))
    assert idx.shape == (500, 6)
    # neighbor ids must be real rows, ascending by distance
    self_d = np.linalg.norm(pts[:, None, :] - pts[idx], axis=-1)
    assert np.all(np.diff(self_d, axis=1) >= -1e-6)


def test_pad_and_flatten_ids_beyond_int32():
    """At >2^31 global points, ids wrap modulo 2^31 but must stay
    NON-NEGATIVE — a negative wrap would silently classify real points as
    padding (the engines test id sign for validity)."""
    from mpi_cuda_largescaleknn_tpu.models.sharding import pad_and_flatten

    base = 2**31 - 3  # global offset of a deep shard in a 10B-point run
    _, ids, counts, _ = pad_and_flatten([random_points(8, seed=4)],
                                        id_bases=[base])
    assert counts == [8]
    assert np.all(ids[:8] >= 0), ids[:8]
    assert ids[0] == 2**31 - 3 and ids[3] == 0  # wrapped, not negative
