import numpy as np
import pytest

from mpi_cuda_largescaleknn_tpu.cli.prepartitioned_main import main as prepart_main
from mpi_cuda_largescaleknn_tpu.cli.unordered_main import main as unordered_main
from mpi_cuda_largescaleknn_tpu.io.native import native_read_slab, native_write_at
from mpi_cuda_largescaleknn_tpu.io.reader import (
    read_file_portion,
    read_list_of_file_names,
)

from .oracle import assert_dist_equal, kth_nn_dist, random_points


def test_read_file_portion_slab_semantics(tmp_path):
    pts = random_points(101, seed=1)
    path = tmp_path / "pts.float3"
    pts.tofile(path)
    slabs = []
    for r in range(4):
        slab, begin, total = read_file_portion(str(path), r, 4)
        assert total == 101
        assert begin == 101 * r // 4  # the reference's integer slab bounds
        slabs.append(slab)
    np.testing.assert_array_equal(np.concatenate(slabs), pts)


def test_read_list_of_file_names(tmp_path):
    p = tmp_path / "list.txt"
    p.write_text("a.bin\nb.bin\nc.bin")  # no trailing newline
    assert read_list_of_file_names(str(p)) == ["a.bin", "b.bin", "c.bin"]


def test_native_io_roundtrip(tmp_path):
    if not __import__("shutil").which("g++"):
        pytest.skip("no C++ toolchain; numpy fallback covers correctness")
    pts = random_points(64, seed=2)
    path = str(tmp_path / "n.float3")
    pts.tofile(path)
    mid = native_read_slab(path, 16, 32)
    np.testing.assert_array_equal(mid, pts[16:48])
    out_path = str(tmp_path / "w.float")
    native_write_at(out_path, 0, pts[:8])
    native_write_at(out_path, 8 * 12, pts[8:16])
    np.testing.assert_array_equal(
        np.fromfile(out_path, np.float32).reshape(-1, 3), pts[:16])


def test_unordered_cli_end_to_end(tmp_path):
    pts = random_points(300, seed=3)
    in_path = str(tmp_path / "in.float3")
    out_path = str(tmp_path / "out.float")
    pts.tofile(in_path)
    rc = unordered_main([in_path, "-o", out_path, "-k", "4", "--shards", "4",
                         "--query-tile", "64", "--point-tile", "64"])
    assert rc == 0
    got = np.fromfile(out_path, np.float32)
    assert got.shape == (300,)
    assert_dist_equal(got, kth_nn_dist(pts, pts, 4))


def test_prepartitioned_cli_end_to_end(tmp_path):
    parts = [random_points(80, seed=10 + i) for i in range(3)]
    names = []
    for i, p in enumerate(parts):
        f = str(tmp_path / f"part{i}.float3")
        p.tofile(f)
        names.append(f)
    list_path = str(tmp_path / "files.txt")
    with open(list_path, "w") as f:
        f.write("\n".join(names) + "\n")
    prefix = str(tmp_path / "dists")
    rc = prepart_main([list_path, "-k", "5", "-o", prefix,
                       "--query-tile", "64", "--point-tile", "64"])
    assert rc == 0
    allp = np.concatenate(parts)
    for i, p in enumerate(parts):
        got = np.fromfile(f"{prefix}_{i:06d}.float", np.float32)
        assert_dist_equal(got, kth_nn_dist(p, allp, 5))


def test_cli_rejects_missing_k(tmp_path, capsys):
    with pytest.raises(SystemExit) as e:
        unordered_main(["in.float3", "-o", "out.float"])
    assert e.value.code == 1
    assert "no k specified" in capsys.readouterr().err


def test_cli_rejects_unknown_flag(capsys):
    with pytest.raises(SystemExit) as e:
        unordered_main(["-q", "bogus"])
    assert e.value.code == 1
    assert "unknown cmdline arg" in capsys.readouterr().err


def test_cli_radius_flag(tmp_path):
    pts = random_points(150, seed=4)
    in_path = str(tmp_path / "in.float3")
    out_path = str(tmp_path / "out.float")
    pts.tofile(in_path)
    rc = unordered_main([in_path, "-o", out_path, "-k", "10", "-r", "0.05",
                         "--shards", "2", "--query-tile", "64",
                         "--point-tile", "64"])
    assert rc == 0
    got = np.fromfile(out_path, np.float32)
    assert_dist_equal(got, kth_nn_dist(pts, pts, 10, max_radius=0.05))
