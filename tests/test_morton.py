"""Morton (Z-order) encoder property tests — the serving admission sort's
foundation (utils/math.py).

Three properties the engine relies on: per-axis order preservation (sorting
by code never inverts a single axis), the pads-last invariant (sentinel rows
sort after every real query, so padded tail buckets stay empty), and the
bit-exact interleave round trip on the full 2^21 grid.
"""

import numpy as np

from mpi_cuda_largescaleknn_tpu.core.types import PAD_SENTINEL
from mpi_cuda_largescaleknn_tpu.utils.math import (
    MORTON_BITS,
    MORTON_PAD_CODE,
    morton_argsort,
    morton_codes,
    morton_deinterleave,
    morton_interleave,
)


class TestInterleaveRoundTrip:
    def test_round_trip_random_grid(self):
        rng = np.random.default_rng(0)
        g = rng.integers(0, 1 << MORTON_BITS, size=(4096, 3)).astype(np.uint64)
        np.testing.assert_array_equal(morton_deinterleave(morton_interleave(g)), g)

    def test_round_trip_extremes(self):
        top = (1 << MORTON_BITS) - 1
        g = np.array([[0, 0, 0], [top, top, top], [top, 0, 0], [0, top, 0],
                      [0, 0, top], [1, 2, 4], [top - 1, 1, top]], np.uint64)
        np.testing.assert_array_equal(morton_deinterleave(morton_interleave(g)), g)

    def test_codes_distinct_on_distinct_grid_points(self):
        rng = np.random.default_rng(1)
        g = rng.integers(0, 1 << MORTON_BITS, size=(2000, 3)).astype(np.uint64)
        g = np.unique(g, axis=0)
        codes = morton_interleave(g)
        assert len(np.unique(codes)) == len(g)

    def test_real_codes_below_pad_code(self):
        top = (1 << MORTON_BITS) - 1
        g = np.full((1, 3), top, np.uint64)
        assert morton_interleave(g)[0] < MORTON_PAD_CODE


class TestAxisOrderPreservation:
    def test_monotone_along_each_axis(self):
        """Fix two grid axes; the code is strictly increasing in the third
        (bits of one axis occupy a fixed stride, other axes contribute a
        constant) — so a Morton sort never inverts a single-axis ordering."""
        rng = np.random.default_rng(2)
        for axis in range(3):
            base = rng.integers(0, 1 << MORTON_BITS, size=(64, 3)).astype(np.uint64)
            walk = np.sort(rng.choice(1 << MORTON_BITS, size=200,
                                      replace=False)).astype(np.uint64)
            for row in base[:8]:
                g = np.tile(row, (len(walk), 1))
                g[:, axis] = walk
                codes = morton_interleave(g)
                assert np.all(np.diff(codes.astype(np.int64)) > 0), axis

    def test_quantized_codes_monotone_along_axis(self):
        lo, hi = np.zeros(3, np.float32), np.ones(3, np.float32)
        x = np.linspace(0, 1, 500, dtype=np.float32)
        pts = np.stack([x, np.full_like(x, 0.25), np.full_like(x, 0.75)], 1)
        codes = morton_codes(pts, lo, hi)
        assert np.all(np.diff(codes.astype(np.int64)) >= 0)


class TestPadsLast:
    def test_sentinel_rows_get_pad_code(self):
        pts = np.full((5, 3), PAD_SENTINEL, np.float32)
        codes = morton_codes(pts, np.zeros(3), np.ones(3))
        assert np.all(codes == MORTON_PAD_CODE)

    def test_pads_sort_last_and_stably(self):
        rng = np.random.default_rng(3)
        pts = rng.random((40, 3)).astype(np.float32)
        pts[[3, 11, 29]] = PAD_SENTINEL
        perm = morton_argsort(pts, np.zeros(3), np.ones(3))
        # all pad rows land at the tail, in input order (stable sort)
        np.testing.assert_array_equal(perm[-3:], [3, 11, 29])
        assert np.all(pts[perm[:-3], 0] < PAD_SENTINEL / 2)

    def test_out_of_box_queries_clamp_not_crash(self):
        pts = np.float32([[-5, 0.5, 0.5], [7, 0.5, 0.5], [0.5, 0.5, 0.5]])
        codes = morton_codes(pts, np.zeros(3), np.ones(3))
        assert codes[0] <= codes[2] <= codes[1]
        assert np.all(codes < MORTON_PAD_CODE)

    def test_degenerate_box_is_safe(self):
        """A single-point index (lo == hi) must not divide by zero; every
        query collapses to one cell."""
        pts = np.float32([[0.5, 0.5, 0.5], [9.0, -3.0, 0.1]])
        codes = morton_codes(pts, np.float32([1, 1, 1]), np.float32([1, 1, 1]))
        assert codes[0] == codes[1] == 0


class TestLocality:
    def test_sorted_halves_are_tighter_than_random_split(self):
        """The point of the sort: contiguous slices of the Morton order have
        smaller AABBs than arbitrary slices of the unsorted batch (made
        deterministic by a fixed seed and a 2x margin on aggregate volume)."""
        rng = np.random.default_rng(4)
        pts = rng.random((512, 3)).astype(np.float32)
        perm = morton_argsort(pts, np.zeros(3), np.ones(3))

        def vol(chunk):
            ext = chunk.max(0) - chunk.min(0)
            return float(np.prod(ext))

        sorted_pts = pts[perm]
        v_sorted = sum(vol(c) for c in np.split(sorted_pts, 8))
        v_unsorted = sum(vol(c) for c in np.split(pts, 8))
        assert v_sorted < 0.5 * v_unsorted, (v_sorted, v_unsorted)
