import jax
import numpy as np
import pytest

from mpi_cuda_largescaleknn_tpu.core.config import KnnConfig
from mpi_cuda_largescaleknn_tpu.models.unordered import UnorderedKNN
from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh

from .oracle import assert_dist_equal, kth_nn_dist, random_points


def _cfg(**kw):
    kw.setdefault("k", 8)
    kw.setdefault("query_tile", 128)
    kw.setdefault("point_tile", 128)
    return KnnConfig(**kw)


def test_ring_matches_oracle_8_shards():
    pts = random_points(1000, seed=1)
    model = UnorderedKNN(_cfg(), mesh=get_mesh(8))
    got = model.run(pts)
    want = kth_nn_dist(pts, pts, 8)
    assert_dist_equal(got, want)


def test_rank_count_invariance():
    # the reference's implicit oracle (SURVEY.md §4): output is independent of
    # the number of ranks. 1 device vs 8 devices must agree.
    pts = random_points(777, seed=2)  # odd size -> uneven slabs
    d1 = UnorderedKNN(_cfg(), mesh=get_mesh(1)).run(pts)
    d8 = UnorderedKNN(_cfg(), mesh=get_mesh(8)).run(pts)
    assert_dist_equal(d8, d1)


def test_ring_tree_engine_matches_bruteforce():
    pts = random_points(600, seed=3)
    dbf = UnorderedKNN(_cfg(), mesh=get_mesh(4)).run(pts)
    dtr = UnorderedKNN(_cfg(engine="tree"), mesh=get_mesh(4)).run(pts)
    assert_dist_equal(dtr, dbf)


def test_cross_shard_heap_fill():
    # k larger than any single shard's point count: heaps can only fill via
    # the cross-round merge
    pts = random_points(64, seed=4)
    model = UnorderedKNN(_cfg(k=20), mesh=get_mesh(8))  # 8 pts/shard
    got = model.run(pts)
    assert_dist_equal(got, kth_nn_dist(pts, pts, 20))


def test_ring_with_radius():
    pts = random_points(400, seed=5)
    r = 0.06
    got = UnorderedKNN(_cfg(k=10, max_radius=r), mesh=get_mesh(8)).run(pts)
    assert_dist_equal(got, kth_nn_dist(pts, pts, 10, max_radius=r))


def test_fewer_points_than_shards():
    pts = random_points(5, seed=6)
    got = UnorderedKNN(_cfg(k=2), mesh=get_mesh(8)).run(pts)
    assert_dist_equal(got, kth_nn_dist(pts, pts, 2))


def test_timers_populated():
    pts = random_points(100, seed=7)
    model = UnorderedKNN(_cfg(k=3), mesh=get_mesh(2))
    model.run(pts)
    rep = model.timers.report()
    assert "ring" in rep and rep["ring"]["seconds"] > 0
