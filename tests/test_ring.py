import jax
import numpy as np
import pytest

from mpi_cuda_largescaleknn_tpu.core.config import KnnConfig
from mpi_cuda_largescaleknn_tpu.models.unordered import UnorderedKNN
from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh

from .oracle import assert_dist_equal, kth_nn_dist, random_points


def _cfg(**kw):
    kw.setdefault("k", 8)
    kw.setdefault("query_tile", 128)
    kw.setdefault("point_tile", 128)
    return KnnConfig(**kw)


def test_ring_matches_oracle_8_shards():
    pts = random_points(1000, seed=1)
    model = UnorderedKNN(_cfg(), mesh=get_mesh(8))
    got = model.run(pts)
    want = kth_nn_dist(pts, pts, 8)
    assert_dist_equal(got, want)


def test_rank_count_invariance():
    # the reference's implicit oracle (SURVEY.md §4): output is independent of
    # the number of ranks. 1 device vs 8 devices must agree.
    pts = random_points(777, seed=2)  # odd size -> uneven slabs
    d1 = UnorderedKNN(_cfg(), mesh=get_mesh(1)).run(pts)
    d8 = UnorderedKNN(_cfg(), mesh=get_mesh(8)).run(pts)
    assert_dist_equal(d8, d1)


def test_ring_tree_engine_matches_bruteforce():
    pts = random_points(600, seed=3)
    dbf = UnorderedKNN(_cfg(), mesh=get_mesh(4)).run(pts)
    dtr = UnorderedKNN(_cfg(engine="tree"), mesh=get_mesh(4)).run(pts)
    assert_dist_equal(dtr, dbf)


def test_cross_shard_heap_fill():
    # k larger than any single shard's point count: heaps can only fill via
    # the cross-round merge
    pts = random_points(64, seed=4)
    model = UnorderedKNN(_cfg(k=20), mesh=get_mesh(8))  # 8 pts/shard
    got = model.run(pts)
    assert_dist_equal(got, kth_nn_dist(pts, pts, 20))


def test_ring_with_radius():
    pts = random_points(400, seed=5)
    r = 0.06
    got = UnorderedKNN(_cfg(k=10, max_radius=r), mesh=get_mesh(8)).run(pts)
    assert_dist_equal(got, kth_nn_dist(pts, pts, 10, max_radius=r))


def test_fewer_points_than_shards():
    pts = random_points(5, seed=6)
    got = UnorderedKNN(_cfg(k=2), mesh=get_mesh(8)).run(pts)
    assert_dist_equal(got, kth_nn_dist(pts, pts, 2))


def test_timers_populated():
    pts = random_points(100, seed=7)
    model = UnorderedKNN(_cfg(k=3), mesh=get_mesh(2))
    model.run(pts)
    rep = model.timers.report()
    assert "ring" in rep and rep["ring"]["seconds"] > 0


def test_resolve_engine_off_tpu():
    from mpi_cuda_largescaleknn_tpu.parallel.ring import resolve_engine

    # CPU fixture: auto must stay on the XLA twin (Pallas would only
    # interpret here); explicit names pass through untouched
    assert resolve_engine("auto") == "tiled"
    for name in ("tiled", "pallas_tiled", "bruteforce", "tree", "pallas"):
        assert resolve_engine(name) == name


def test_measure_exchange_bandwidth_method():
    from mpi_cuda_largescaleknn_tpu.parallel.ring import (
        measure_exchange_bandwidth,
    )

    rep = measure_exchange_bandwidth(get_mesh(8), 1000, bucket_size=64,
                                     reps=3)
    assert rep["num_shards"] == 8
    # bucketed shard bytes: B*S*(12+4) + 2*B*12 for the bounds
    from mpi_cuda_largescaleknn_tpu.ops.partition import choose_buckets
    b, s = choose_buckets(1000, 64)
    assert rep["shard_bytes"] == b * s * 16 + 2 * b * 12
    assert rep["exchange_GB_per_sec_per_link"] > 0
    # round_seconds is a rounded control-subtracted delta: on a contended
    # host it can legitimately round to 0.0 — only its sign is invariant
    assert rep["round_seconds"] >= 0


def test_partition_sharded_bit_identical_to_partition_points():
    """The hoisted per-shard partition (one compiled level program) must be
    BIT-identical to tracing partition_points per shard — the checkpoint
    fingerprint and every engine's tie order depend on it."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mpi_cuda_largescaleknn_tpu.ops.partition import partition_points
    from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
    from mpi_cuda_largescaleknn_tpu.parallel.ring import partition_sharded

    rng = np.random.default_rng(77)
    shards = len(jax.devices())
    npad = 192
    pts = rng.random((shards * npad, 3)).astype(np.float32)
    ids = np.arange(shards * npad, dtype=np.int32)
    q = partition_sharded(pts, ids, get_mesh(shards), 32)
    b_local = q.pts.shape[0] // shards
    for r in range(shards):
        ref = partition_points(jnp.asarray(pts[r * npad:(r + 1) * npad]),
                               jnp.asarray(ids[r * npad:(r + 1) * npad]),
                               bucket_size=32)
        sl = slice(r * b_local, (r + 1) * b_local)
        np.testing.assert_array_equal(np.asarray(q.pts[sl]),
                                      np.asarray(ref.pts))
        np.testing.assert_array_equal(np.asarray(q.ids[sl]),
                                      np.asarray(ref.ids))
        np.testing.assert_array_equal(np.asarray(q.pos[sl]),
                                      np.asarray(ref.pos))
        np.testing.assert_array_equal(np.asarray(q.lower[sl]),
                                      np.asarray(ref.lower))
        np.testing.assert_array_equal(np.asarray(q.upper[sl]),
                                      np.asarray(ref.upper))
