"""MXU matmul-form distance scoring (ops/distance.py) vs the elementwise
kernels: the bf16 score + exact f32 rescore must be BIT-IDENTICAL — final
(dist2, idx) including tie ids — to the f32 elementwise path, across
D in {3, 8, 64}, shard counts R in {1, 2, 4}, both merge placements, and
the Pallas / XLA tiled twins; plus the adversarial bf16-ulp property test
(points closer than a bf16 ulp at large ||p|| tie in the approximate score,
and the exact rescore must still recover the exact top-k)."""

import numpy as np
import pytest

import jax.numpy as jnp

from mpi_cuda_largescaleknn_tpu.core.types import PAD_SENTINEL
from mpi_cuda_largescaleknn_tpu.ops.brute_force import knn_update_bruteforce
from mpi_cuda_largescaleknn_tpu.ops.candidates import (
    extract_final_result,
    init_candidates,
)
from mpi_cuda_largescaleknn_tpu.ops.distance import (
    elementwise_dist2,
    mxu_scores,
    norms2,
    rescore_width,
    score_tile,
)
from mpi_cuda_largescaleknn_tpu.ops.partition import (
    partition_points,
    scatter_back,
)
from mpi_cuda_largescaleknn_tpu.ops.tiled import knn_update_tiled
from tests.oracle import kth_nn_dist, pairwise_dist2_np, random_points

DIMS = (3, 8, 64)


def _pallas_traversal_or_skip():
    """The Pallas traversal kernel needs either real TPU Mosaic or an
    interpret mode whose DMA-state discharge this jax pin implements; on
    the container pin it raises NotImplementedError (the known pallas-API
    drift — ROADMAP.md). Skip instead of double-counting that failure."""
    from mpi_cuda_largescaleknn_tpu.ops.pallas.knn_tiled import (
        knn_update_tiled_pallas,
    )

    pts = random_points(64, seed=11)
    q = partition_points(jnp.asarray(pts), bucket_size=16)
    st = init_candidates(q.num_buckets * q.bucket_size, 4)
    try:
        out = knn_update_tiled_pallas(st, q, q)
        np.asarray(out.dist2)
    except NotImplementedError:
        pytest.skip("pallas interpret mode unsupported on this jax pin "
                    "(pre-existing API drift, see ROADMAP.md)")
    except Exception as e:  # pragma: no cover - other drift spellings
        pytest.skip(f"pallas traversal unavailable on this jax pin: "
                    f"{type(e).__name__}: {e}")
    return knn_update_tiled_pallas


def _with_dups_and_pads(d, seed, n=450, dups=24):
    """A point set with duplicate points (exact ties) and a count that is
    NOT a multiple of any bucket/tile size (ragged sentinel pads)."""
    pts = random_points(n, seed=seed, dim=d)
    pts[n - dups:n] = pts[: dups]  # exact duplicates -> exact tie classes
    return pts


class TestScoreTile:
    @pytest.fixture(autouse=True)
    def _force_mxu_at_all_dims(self, monkeypatch):
        # exercise the matmul-form machinery at EVERY D (the shipped
        # default falls back to the exact path below mxu_min_dim()=16,
        # where the MXU cannot win — ops/distance.py)
        monkeypatch.setenv("LSK_MXU_MIN_DIM", "1")

    def test_elementwise_matches_legacy_3d_tree(self):
        """The D-generic scorer at D=3 is the exact (dx2+dy2)+dz2 tree —
        bitwise equal to the numpy oracle (the contraction guard makes XLA
        round every step like numpy does)."""
        q = random_points(100, seed=0)
        p = random_points(300, seed=1)
        import jax

        got = np.asarray(jax.jit(elementwise_dist2)(jnp.asarray(q),
                                                    jnp.asarray(p)))
        np.testing.assert_array_equal(got, pairwise_dist2_np(q, p))

    @pytest.mark.parametrize("d", DIMS)
    def test_bf16_survivors_are_exactly_rescored(self, d):
        """score_tile bf16 returns EXACT f32 distances for its survivors:
        every (value, id) pair it emits equals the elementwise tile's value
        at that id — bit for bit."""
        import jax

        q = random_points(40, seed=2, dim=d)
        p = random_points(600, seed=3, dim=d)
        k = 8
        f = jax.jit(lambda q, p: score_tile(
            q, p, jnp.arange(600, dtype=jnp.int32), k, score_dtype="bf16"))
        d2, idx = f(jnp.asarray(q), jnp.asarray(p))
        d2, idx = np.asarray(d2), np.asarray(idx)
        assert d2.shape == (40, rescore_width(k, 600))
        full = np.asarray(jax.jit(elementwise_dist2)(jnp.asarray(q),
                                                     jnp.asarray(p)))
        np.testing.assert_array_equal(d2, np.take_along_axis(full, idx,
                                                             axis=1))
        # lane order restored: survivor ids ascend per row
        assert np.all(np.diff(idx, axis=1) > 0)

    def test_mxu_scores_are_actually_approximate(self):
        """Sanity that the property tests below test something: the bf16
        matmul-form scores really do deviate from the exact distances (the
        rescore is earning its keep)."""
        q = random_points(64, seed=4, scale=100.0)
        p = random_points(512, seed=5, scale=100.0)
        approx = np.asarray(mxu_scores(jnp.asarray(q), jnp.asarray(p)))
        exact = pairwise_dist2_np(q, p)
        assert not np.array_equal(approx, exact)
        # but they are close in the relative-to-norms sense
        scale = float(np.max(norms2(jnp.asarray(p))))
        assert np.max(np.abs(approx - exact)) < 0.05 * scale


class TestBruteForceMxu:
    """Satellite: the D-generic brute-force tile layout (PAD_SENTINEL
    padding path included), with the D=8 test the issue asks for."""

    @pytest.fixture(autouse=True)
    def _force_mxu_at_all_dims(self, monkeypatch):
        # exercise the matmul-form machinery at EVERY D (the shipped
        # default falls back to the exact path below mxu_min_dim()=16,
        # where the MXU cannot win — ops/distance.py)
        monkeypatch.setenv("LSK_MXU_MIN_DIM", "1")


    @pytest.mark.parametrize("d", DIMS)
    def test_bitwise_parity_and_oracle(self, d):
        pts = _with_dups_and_pads(d, seed=6)
        qs = random_points(77, seed=7, dim=d)  # ragged vs 32/64 tiles
        k = 8
        st = init_candidates(len(qs), k)
        f32 = knn_update_bruteforce(st, jnp.asarray(qs), jnp.asarray(pts),
                                    query_tile=32, point_tile=64)
        b16 = knn_update_bruteforce(st, jnp.asarray(qs), jnp.asarray(pts),
                                    query_tile=32, point_tile=64,
                                    score_dtype="bf16")
        np.testing.assert_array_equal(np.asarray(f32.dist2),
                                      np.asarray(b16.dist2))
        np.testing.assert_array_equal(np.asarray(f32.idx),
                                      np.asarray(b16.idx))
        want = np.sqrt(np.sort(pairwise_dist2_np(qs, pts), axis=1)[:, k - 1])
        np.testing.assert_array_equal(
            np.sqrt(np.asarray(b16.dist2)[:, k - 1]), want)

    def test_max_radius_parity_d8(self):
        pts = random_points(400, seed=8, dim=8)
        qs = random_points(50, seed=9, dim=8)
        r = 0.5  # hits both filled and under-full rows at D=8 in [0,1]^8
        st = init_candidates(len(qs), 6, max_radius=r)
        f32 = knn_update_bruteforce(st, jnp.asarray(qs), jnp.asarray(pts))
        b16 = knn_update_bruteforce(st, jnp.asarray(qs), jnp.asarray(pts),
                                    score_dtype="bf16")
        np.testing.assert_array_equal(np.asarray(f32.dist2),
                                      np.asarray(b16.dist2))
        np.testing.assert_array_equal(np.asarray(f32.idx),
                                      np.asarray(b16.idx))
        assert np.any(np.asarray(f32.idx) == -1)  # radius actually bites


class TestTiledMxu:
    """The XLA traversal twin: bf16 vs f32 bit-parity across the full
    local matrix (ties, ragged pads, duplicate points, max_radius, both
    tie disciplines), D in {3, 8, 64}."""

    @pytest.fixture(autouse=True)
    def _force_mxu_at_all_dims(self, monkeypatch):
        # exercise the matmul-form machinery at EVERY D (the shipped
        # default falls back to the exact path below mxu_min_dim()=16,
        # where the MXU cannot win — ops/distance.py)
        monkeypatch.setenv("LSK_MXU_MIN_DIM", "1")


    @pytest.mark.parametrize("d", DIMS)
    @pytest.mark.parametrize("canonical", [False, True])
    def test_bitwise_parity(self, d, canonical):
        pts = _with_dups_and_pads(d, seed=10 + d)
        k = 8
        q = partition_points(jnp.asarray(pts), bucket_size=32)
        st = init_candidates(q.num_buckets * q.bucket_size, k)
        f32, tiles_f = knn_update_tiled(st, q, q, with_stats=True,
                                        canonical_ties=canonical)
        b16, tiles_b = knn_update_tiled(st, q, q, with_stats=True,
                                        canonical_ties=canonical,
                                        score_dtype="bf16")
        np.testing.assert_array_equal(np.asarray(f32.dist2),
                                      np.asarray(b16.dist2))
        np.testing.assert_array_equal(np.asarray(f32.idx),
                                      np.asarray(b16.idx))
        # same schedule, same prune radii -> same executed-tile count
        assert int(tiles_f) == int(tiles_b)
        # and the result is oracle-exact
        dists = extract_final_result(f32).reshape(q.num_buckets,
                                                  q.bucket_size)
        got = np.asarray(scatter_back(dists, q.pos, len(pts), fill=jnp.inf))
        np.testing.assert_array_equal(got, kth_nn_dist(pts, pts, k))

    @pytest.mark.parametrize("d", (3, 8))
    def test_max_radius_parity(self, d):
        pts = random_points(300, seed=20, dim=d)
        r = 0.25 if d == 3 else 0.8
        q = partition_points(jnp.asarray(pts), bucket_size=32)
        st = init_candidates(q.num_buckets * q.bucket_size, 5, max_radius=r)
        f32 = knn_update_tiled(st, q, q)
        b16 = knn_update_tiled(st, q, q, score_dtype="bf16")
        np.testing.assert_array_equal(np.asarray(f32.dist2),
                                      np.asarray(b16.dist2))
        np.testing.assert_array_equal(np.asarray(f32.idx),
                                      np.asarray(b16.idx))

    def test_precomputed_norms_change_nothing(self, ):
        pts = random_points(256, seed=21, dim=8)
        q = partition_points(jnp.asarray(pts), bucket_size=32)
        st = init_candidates(q.num_buckets * q.bucket_size, 4)
        a = knn_update_tiled(st, q, q, score_dtype="bf16")
        b = knn_update_tiled(st, q, q, score_dtype="bf16",
                             point_norms2=norms2(q.pts))
        np.testing.assert_array_equal(np.asarray(a.dist2), np.asarray(b.dist2))
        np.testing.assert_array_equal(np.asarray(a.idx), np.asarray(b.idx))

    def test_full_stats_fold_counter_is_real(self):
        """with_stats='full' returns an honest fold counter: positive when
        merges ran, bounded by the tile-count upper bound, and ZERO folds
        exactly when zero tiles executed (the old stub fabricated 0)."""
        pts = random_points(300, seed=22)
        q = partition_points(jnp.asarray(pts), bucket_size=32)
        st = init_candidates(q.num_buckets * q.bucket_size, 4)
        out, tiles, folds = knn_update_tiled(st, q, q, with_stats="full")
        assert int(tiles) > 0 and int(folds) > 0
        assert int(folds) <= int(tiles)  # a fold merges >= 1 tile (chunk*V)
        # all-padding queries -> traversal prunes everything immediately
        pad = jnp.full((64, 3), PAD_SENTINEL, jnp.float32)
        qp = partition_points(pad, bucket_size=32)
        stp = init_candidates(qp.num_buckets * qp.bucket_size, 4)
        _, tiles0, folds0 = knn_update_tiled(stp, qp, q, with_stats="full")
        assert int(tiles0) == 0 and int(folds0) == 0


class TestPallasMxu:
    """The Pallas twin: widened-row approx fold + post-kernel exact
    rescore must match its own f32 mode bit for bit (canonical rows)."""

    @pytest.fixture(autouse=True)
    def _force_mxu_at_all_dims(self, monkeypatch):
        # exercise the matmul-form machinery at EVERY D (the shipped
        # default falls back to the exact path below mxu_min_dim()=16,
        # where the MXU cannot win — ops/distance.py)
        monkeypatch.setenv("LSK_MXU_MIN_DIM", "1")


    @pytest.mark.parametrize("d", DIMS)
    def test_bitwise_parity(self, d):
        kernel = _pallas_traversal_or_skip()
        pts = _with_dups_and_pads(d, seed=30 + d)
        k = 8
        q = partition_points(jnp.asarray(pts), bucket_size=32)
        st = init_candidates(q.num_buckets * q.bucket_size, k)
        f32 = kernel(st, q, q, canonical_ties=True)
        b16 = kernel(st, q, q, canonical_ties=True, score_dtype="bf16")
        np.testing.assert_array_equal(np.asarray(f32.dist2),
                                      np.asarray(b16.dist2))
        np.testing.assert_array_equal(np.asarray(f32.idx),
                                      np.asarray(b16.idx))

    def test_warm_start_parity_bf16(self):
        kernel = _pallas_traversal_or_skip()
        from mpi_cuda_largescaleknn_tpu.ops.tiled import warm_start_self

        pts = random_points(400, seed=33)
        q = partition_points(jnp.asarray(pts), bucket_size=32)
        st = init_candidates(q.num_buckets * q.bucket_size, 8)
        cold = kernel(st, q, q)
        warm = kernel(warm_start_self(q, 8), q, q, skip_self=jnp.int32(1),
                      score_dtype="bf16")
        real = np.asarray(q.ids).reshape(-1) >= 0
        np.testing.assert_array_equal(np.asarray(warm.dist2)[real],
                                      np.asarray(cold.dist2)[real])


class TestRingMxu:
    """Shard counts R in {1, 2, 4} x merge placements: the full ring /
    replicate-traverse-merge drivers under bf16 vs f32, bit-identical."""

    @pytest.fixture(autouse=True)
    def _force_mxu_at_all_dims(self, monkeypatch):
        # exercise the matmul-form machinery at EVERY D (the shipped
        # default falls back to the exact path below mxu_min_dim()=16,
        # where the MXU cannot win — ops/distance.py)
        monkeypatch.setenv("LSK_MXU_MIN_DIM", "1")


    @pytest.mark.parametrize("shards", [1, 2, 4])
    def test_ring_knn_parity(self, shards):
        import jax
        from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
        from mpi_cuda_largescaleknn_tpu.parallel.ring import ring_knn

        mesh = get_mesh(shards)
        pts = random_points(shards * 96, seed=40 + shards, dim=8)
        ids = np.arange(len(pts), dtype=np.int32)
        k = 4
        a, ca = ring_knn(pts, ids, k, mesh, bucket_size=16,
                         return_candidates=True)
        b, cb = ring_knn(pts, ids, k, mesh, bucket_size=16,
                         score_dtype="bf16", return_candidates=True)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(np.asarray(ca.dist2),
                                      np.asarray(cb.dist2))
        np.testing.assert_array_equal(np.asarray(ca.idx), np.asarray(cb.idx))

    @pytest.mark.parametrize("merge", ["host", "device"])
    def test_chunked_merge_parity(self, merge):
        from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
        from mpi_cuda_largescaleknn_tpu.parallel.ring import ring_knn_chunked

        mesh = get_mesh(4)
        pts = random_points(4 * 64, seed=50, dim=8)
        ids = np.arange(len(pts), dtype=np.int32)
        a = ring_knn_chunked(pts, ids, 4, mesh, chunk_rows=32,
                             bucket_size=16, merge=merge)
        b = ring_knn_chunked(pts, ids, 4, mesh, chunk_rows=32,
                             bucket_size=16, merge=merge,
                             score_dtype="bf16")
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestServeMxu:
    """The serving engine end to end: score_dtype in the AOT key, the
    precomputed-norms resident operand, per-mode tile counters, and a
    D=8 index served through the full dispatch/complete path."""

    @pytest.fixture(autouse=True)
    def _force_mxu_at_all_dims(self, monkeypatch):
        # exercise the matmul-form machinery at EVERY D (the shipped
        # default falls back to the exact path below mxu_min_dim()=16,
        # where the MXU cannot win — ops/distance.py)
        monkeypatch.setenv("LSK_MXU_MIN_DIM", "1")


    def test_engine_parity_and_counters(self):
        from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
        from mpi_cuda_largescaleknn_tpu.serve.engine import ResidentKnnEngine

        pts = random_points(1024, seed=60)
        mesh = get_mesh(2)
        qs = random_points(40, seed=61)
        eng_f = ResidentKnnEngine(pts, 8, mesh=mesh, engine="tiled",
                                  bucket_size=32, max_batch=64, min_batch=16)
        eng_b = ResidentKnnEngine(pts, 8, mesh=mesh, engine="tiled",
                                  bucket_size=32, max_batch=64, min_batch=16,
                                  score_dtype="bf16")
        df, nf = eng_f.query(qs)
        db, nb = eng_b.query(qs)
        np.testing.assert_array_equal(df, db)
        np.testing.assert_array_equal(nf, nb)
        sf, sb = eng_f.stats(), eng_b.stats()
        assert sf["score_dtype"] == "f32" and sb["score_dtype"] == "bf16"
        # per-mode attribution: each engine counts under ITS scorer only
        assert sf["tiles_executed_vpu"] == sf["tiles_executed"] > 0
        assert sf["tiles_executed_mxu"] == 0
        assert sb["tiles_executed_mxu"] == sb["tiles_executed"] > 0
        assert sb["tiles_executed_vpu"] == 0
        # distinct AOT programs, one compile each (key carries the dtype)
        assert eng_f.compile_count == 1 and eng_b.compile_count == 1

    def test_engine_serves_d8(self):
        from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
        from mpi_cuda_largescaleknn_tpu.serve.engine import ResidentKnnEngine

        pts = random_points(512, seed=62, dim=8)
        eng = ResidentKnnEngine(pts, 4, mesh=get_mesh(1), engine="tiled",
                                bucket_size=32, max_batch=32, min_batch=8,
                                score_dtype="bf16")
        assert eng.dim == 8 and not eng.sort_queries
        qs = random_points(19, seed=63, dim=8)
        dists, nbrs = eng.query(qs)
        want = np.sqrt(np.sort(pairwise_dist2_np(qs, pts), axis=1)[:, 3])
        np.testing.assert_array_equal(dists, want)


class TestBf16UlpProperty:
    """The adversarial exactness property: a cluster of points separated
    by LESS than a bf16 ulp at large ||p|| ties in the approximate score
    (top_k then picks by lane, blind to the true order), and the exact f32
    rescore must still recover the exact top-k — while a hypothetical
    no-rescore bf16 path provably could not."""

    @pytest.fixture(autouse=True)
    def _force_mxu_at_all_dims(self, monkeypatch):
        # exercise the matmul-form machinery at EVERY D (the shipped
        # default falls back to the exact path below mxu_min_dim()=16,
        # where the MXU cannot win — ops/distance.py)
        monkeypatch.setenv("LSK_MXU_MIN_DIM", "1")


    @pytest.mark.parametrize("d", (3, 64))
    def test_rescore_recovers_exact_topk(self, d):
        rng = np.random.default_rng(70 + d)
        k = 8
        base = np.full((d,), 512.0, np.float32)  # bf16 ulp at 512 is 2.0
        # 2k cluster points, pairwise distances ~1e-3 — far below the bf16
        # score error (~||p|| * ulp); lane order is randomized so approx
        # tie-breaking cannot accidentally equal the true order
        cluster = base[None, :] + (rng.random((2 * k, d)).astype(np.float32)
                                   * 1e-3)
        filler = rng.random((400, d)).astype(np.float32)  # near origin: far
        pts = np.concatenate([cluster, filler]).astype(np.float32)
        perm = rng.permutation(len(pts))
        pts = pts[perm]
        q = (base + 0.5).astype(np.float32)[None, :]
        # the approximate scores genuinely cannot rank the cluster
        approx = np.asarray(mxu_scores(jnp.asarray(q), jnp.asarray(pts)))[0]
        exact = pairwise_dist2_np(q, pts)[0]
        cl = np.argsort(exact)[: 2 * k]
        assert len(np.unique(approx[cl])) < 2 * k or not np.array_equal(
            np.argsort(approx[cl], kind="stable"),
            np.argsort(exact[cl], kind="stable"))
        # ...but the rescored engine recovers the exact top-k, bitwise
        st = init_candidates(1, k)
        f32 = knn_update_bruteforce(st, jnp.asarray(q), jnp.asarray(pts))
        b16 = knn_update_bruteforce(st, jnp.asarray(q), jnp.asarray(pts),
                                    score_dtype="bf16")
        np.testing.assert_array_equal(np.asarray(f32.dist2),
                                      np.asarray(b16.dist2))
        np.testing.assert_array_equal(np.asarray(f32.idx),
                                      np.asarray(b16.idx))
        np.testing.assert_array_equal(np.asarray(b16.dist2)[0],
                                      np.sort(exact, kind="stable")[:k])

    def test_identical_points_tie_by_id_under_canonical(self):
        """Exact duplicates at large norm: every copy ties in BOTH exact
        and approx scores; canonical mode must keep the smallest ids."""
        d, k = 8, 4
        base = np.full((d,), 512.0, np.float32)
        pts = np.concatenate([np.tile(base, (6, 1)),
                              random_points(200, seed=71, dim=d)])
        q = partition_points(jnp.asarray(np.concatenate(
            [base[None, :] + 0.25, random_points(63, seed=72, dim=d)])),
            bucket_size=16)
        p = partition_points(jnp.asarray(pts), bucket_size=16)
        st = init_candidates(q.num_buckets * q.bucket_size, k)
        f32 = knn_update_tiled(st, q, p, canonical_ties=True)
        b16 = knn_update_tiled(st, q, p, canonical_ties=True,
                               score_dtype="bf16")
        np.testing.assert_array_equal(np.asarray(f32.idx),
                                      np.asarray(b16.idx))
        np.testing.assert_array_equal(np.asarray(f32.dist2),
                                      np.asarray(b16.dist2))
        # the query row nearest the dup stack holds ids 0..3 (smallest of
        # the 6 tied copies) under the canonical order
        qpos = np.asarray(q.pos).reshape(-1)
        row = int(np.where(qpos == 0)[0][0])
        np.testing.assert_array_equal(np.asarray(f32.idx)[row],
                                      np.arange(k))


class TestPartitionDGeneric:
    @pytest.mark.parametrize("d", (8, 64))
    def test_partition_is_permutation(self, d):
        pts = random_points(301, seed=80, dim=d)
        q = partition_points(jnp.asarray(pts), bucket_size=32)
        pos = np.asarray(q.pos).ravel()
        real = pos[pos >= 0]
        assert sorted(real) == list(range(301))
        flat = np.asarray(q.pts).reshape(-1, d)
        np.testing.assert_array_equal(flat[pos >= 0], pts[real])

    def test_d3_partition_unchanged(self):
        """D-generic rewrite must reproduce the 3-D partition exactly
        (bucket order, tie order, bounds)."""
        pts = random_points(500, seed=81)
        q = partition_points(jnp.asarray(pts), bucket_size=32)
        # the invariant the serving stack depends on: every bucket's points
        # sit inside its AABB and pads carry inverted bounds
        p = np.asarray(q.pts)
        lo, hi = np.asarray(q.lower), np.asarray(q.upper)
        for b in range(q.num_buckets):
            real = p[b][p[b, :, 0] < PAD_SENTINEL / 2]
            if len(real):
                assert np.all(real >= lo[b] - 1e-6)
                assert np.all(real <= hi[b] + 1e-6)
