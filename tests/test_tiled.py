"""Bucketed nearest-first engine (ops/partition.py + ops/tiled.py) vs oracle."""

import jax.numpy as jnp
import numpy as np
import pytest

from mpi_cuda_largescaleknn_tpu.core.types import PAD_SENTINEL, CandidateState
from mpi_cuda_largescaleknn_tpu.ops.candidates import (
    extract_final_result,
    init_candidates,
)
from mpi_cuda_largescaleknn_tpu.ops.partition import (
    partition_points,
    scatter_back,
)
from mpi_cuda_largescaleknn_tpu.ops.tiled import knn_update_tiled
from tests.oracle import assert_dist_equal, kth_nn_dist, random_points


def tiled_self_knn(pts, k, max_radius=np.inf, bucket_size=32):
    """Single-shard tiled kNN of a point set against itself."""
    q = partition_points(jnp.asarray(pts), bucket_size=bucket_size)
    state = init_candidates(q.num_buckets * q.bucket_size, k, max_radius)
    state = knn_update_tiled(state, q, q)
    d = extract_final_result(state).reshape(q.num_buckets, q.bucket_size)
    return np.asarray(scatter_back(d, q.pos, len(pts), fill=jnp.inf))


class TestPartition:
    def test_partition_is_permutation(self):
        pts = random_points(501, seed=3)
        q = partition_points(jnp.asarray(pts), bucket_size=32)
        pos = np.asarray(q.pos).ravel()
        real = pos[pos >= 0]
        assert sorted(real) == list(range(501))
        # each real bucketed row equals the input row it claims to be
        flat_pts = np.asarray(q.pts).reshape(-1, 3)
        np.testing.assert_array_equal(flat_pts[pos >= 0], pts[real])
        # ids carry through identically
        ids = np.asarray(q.ids).ravel()
        np.testing.assert_array_equal(ids[pos >= 0], real)

    def test_bounds_cover_their_points(self):
        pts = random_points(300, seed=4)
        q = partition_points(jnp.asarray(pts), bucket_size=16)
        p = np.asarray(q.pts)
        lo, hi = np.asarray(q.lower), np.asarray(q.upper)
        for b in range(q.num_buckets):
            real = p[b][p[b, :, 0] < PAD_SENTINEL / 2]
            if len(real) == 0:
                assert np.all(np.isinf(lo[b])) and np.all(np.isinf(hi[b]))
            else:
                assert np.all(real >= lo[b] - 1e-6)
                assert np.all(real <= hi[b] + 1e-6)

    def test_scatter_back_roundtrip(self):
        pts = random_points(77, seed=5)
        q = partition_points(jnp.asarray(pts), bucket_size=8)
        vals = q.pts[:, :, 0]
        back = np.asarray(scatter_back(vals, q.pos, 77, fill=jnp.inf))
        np.testing.assert_array_equal(back, pts[:, 0])


class TestTiledEngine:
    @pytest.mark.parametrize("n,k", [(100, 1), (257, 8), (1000, 13), (64, 64)])
    def test_matches_oracle(self, n, k):
        pts = random_points(n, seed=n)
        want = kth_nn_dist(pts, pts, k)
        assert_dist_equal(tiled_self_knn(pts, k), want)

    def test_k_exceeds_n_gives_inf(self):
        pts = random_points(10, seed=1)
        got = tiled_self_knn(pts, 32)
        assert np.all(np.isinf(got))

    def test_max_radius_cutoff(self):
        pts = random_points(400, seed=9, scale=4.0)
        r = 0.35
        want = kth_nn_dist(pts, pts, 6, max_radius=r)
        assert_dist_equal(tiled_self_knn(pts, 6, max_radius=r), want)

    def test_clustered_data(self):
        # two far-apart clusters: pruning must never cut a true neighbor
        rng = np.random.default_rng(11)
        a = (rng.random((150, 3)) * 0.1).astype(np.float32)
        b = (rng.random((150, 3)) * 0.1 + 50.0).astype(np.float32)
        pts = np.concatenate([a, b]).astype(np.float32)
        want = kth_nn_dist(pts, pts, 5)
        assert_dist_equal(tiled_self_knn(pts, 5, bucket_size=16), want)

    def test_duplicate_points_ties(self):
        pts = np.repeat(random_points(40, seed=13), 4, axis=0)
        want = kth_nn_dist(pts, pts, 7)
        assert_dist_equal(tiled_self_knn(pts, 7, bucket_size=16), want)

    def test_adoption_across_updates(self):
        # folding two disjoint shards sequentially == one-shot over the union
        pts = random_points(300, seed=17)
        a, b = pts[:151], pts[151:]
        k = 9
        q = partition_points(jnp.asarray(pts), bucket_size=16)
        pa = partition_points(jnp.asarray(a), jnp.arange(151, dtype=jnp.int32),
                              bucket_size=16)
        pb = partition_points(jnp.asarray(b),
                              jnp.arange(151, 300, dtype=jnp.int32),
                              bucket_size=16)
        state = init_candidates(q.num_buckets * q.bucket_size, k)
        state = knn_update_tiled(state, q, pa)
        state = knn_update_tiled(state, q, pb)
        d = extract_final_result(state).reshape(q.num_buckets, q.bucket_size)
        got = np.asarray(scatter_back(d, q.pos, len(pts), fill=jnp.inf))
        assert_dist_equal(got, kth_nn_dist(pts, pts, k))

    def test_neighbor_ids_are_true_neighbors(self):
        pts = random_points(120, seed=19)
        k = 4
        q = partition_points(jnp.asarray(pts), bucket_size=16)
        state = init_candidates(q.num_buckets * q.bucket_size, k)
        state = knn_update_tiled(state, q, q)
        bs = (q.num_buckets, q.bucket_size)
        idx = np.asarray(scatter_back(state.idx.reshape(bs + (k,)), q.pos,
                                      len(pts), fill=-1))
        d2 = np.asarray(scatter_back(state.dist2.reshape(bs + (k,)), q.pos,
                                     len(pts), fill=jnp.inf))
        from tests.oracle import pairwise_dist2_np
        full = pairwise_dist2_np(pts, pts)
        for i in range(len(pts)):
            np.testing.assert_allclose(
                np.sort(d2[i]), np.sort(full[i])[:k], rtol=5e-7)
            assert idx[i, 0] == i or d2[i, 0] == 0.0  # self is the 1-NN


class TestTiledInRing:
    def test_ring_tiled_matches_oracle_8dev(self):
        import jax

        from mpi_cuda_largescaleknn_tpu.core.config import KnnConfig
        from mpi_cuda_largescaleknn_tpu.models.unordered import UnorderedKNN
        from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh

        pts = random_points(803, seed=23)
        k = 6
        cfg = KnnConfig(k=k, engine="tiled", bucket_size=16)
        got = UnorderedKNN(cfg, mesh=get_mesh(len(jax.devices()))).run(pts)
        assert_dist_equal(got, kth_nn_dist(pts, pts, k))

    def test_demand_tiled_matches_oracle(self):
        from mpi_cuda_largescaleknn_tpu.core.config import KnnConfig
        from mpi_cuda_largescaleknn_tpu.models.prepartitioned import (
            PrePartitionedKNN,
        )
        from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh

        # 8 spatially-coherent partitions (sorted by x then slabbed)
        pts = random_points(640, seed=31)
        pts = pts[np.argsort(pts[:, 0], kind="stable")]
        parts = [pts[i * 80:(i + 1) * 80] for i in range(8)]
        cfg = KnnConfig(k=5, engine="tiled", bucket_size=16)
        model = PrePartitionedKNN(cfg, mesh=get_mesh(8))
        got = np.concatenate(model.run(parts))
        assert_dist_equal(got, kth_nn_dist(pts, pts, 5))
        assert model.last_stats["rounds"] <= 8

    def test_ring_tiled_matches_single_device(self):
        from mpi_cuda_largescaleknn_tpu.core.config import KnnConfig
        from mpi_cuda_largescaleknn_tpu.models.unordered import UnorderedKNN
        from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh

        pts = random_points(500, seed=29)
        cfg = KnnConfig(k=5, engine="tiled", bucket_size=16)
        one = UnorderedKNN(cfg, mesh=get_mesh(1)).run(pts)
        eight = UnorderedKNN(cfg, mesh=get_mesh(8)).run(pts)
        np.testing.assert_array_equal(one, eight)


class TestWarmStart:
    """warm_start_self + skip_self (the cold-heap fold-pass eliminator the
    ring/demand self-join drivers use) against the cold traversal."""

    def test_warm_plus_skip_bitidentical_to_cold(self):
        from mpi_cuda_largescaleknn_tpu.ops.tiled import warm_start_self

        pts = random_points(700, seed=41)
        k = 7
        q = partition_points(jnp.asarray(pts), bucket_size=32)
        cold = knn_update_tiled(
            init_candidates(q.num_buckets * q.bucket_size, k), q, q)
        warm0 = warm_start_self(q, k)
        warm = knn_update_tiled(warm0, q, q, skip_self=jnp.int32(1))
        # real rows only: pad rows may differ (warm start folds pad-vs-pad
        # zero distances the cold path masks; drivers trim pad rows anyway)
        real = np.asarray(q.ids).reshape(-1) >= 0
        np.testing.assert_array_equal(np.asarray(warm.dist2)[real],
                                      np.asarray(cold.dist2)[real])
        np.testing.assert_array_equal(np.asarray(warm.idx)[real],
                                      np.asarray(cold.idx)[real])

    def test_warm_plus_skip_bitidentical_pallas(self):
        from mpi_cuda_largescaleknn_tpu.ops.pallas.knn_tiled import (
            knn_update_tiled_pallas,
        )
        from mpi_cuda_largescaleknn_tpu.ops.tiled import warm_start_self

        pts = random_points(600, seed=42)
        k = 5
        q = partition_points(jnp.asarray(pts), bucket_size=32)
        cold = knn_update_tiled_pallas(
            init_candidates(q.num_buckets * q.bucket_size, k), q, q)
        warm0 = warm_start_self(q, k)
        warm, visits = knn_update_tiled_pallas(
            warm0, q, q, skip_self=jnp.int32(1), with_stats=True)
        cold2, visits_cold = knn_update_tiled_pallas(
            init_candidates(q.num_buckets * q.bucket_size, k), q, q,
            with_stats=True)
        real = np.asarray(q.ids).reshape(-1) >= 0
        np.testing.assert_array_equal(np.asarray(warm.dist2)[real],
                                      np.asarray(cold.dist2)[real])
        np.testing.assert_array_equal(np.asarray(warm.idx)[real],
                                      np.asarray(cold.idx)[real])
        # the skipped self buckets must show up as fewer counted visits
        assert int(visits) < int(visits_cold)

    def test_warm_start_respects_max_radius(self):
        from mpi_cuda_largescaleknn_tpu.ops.tiled import warm_start_self

        pts = random_points(400, seed=43)
        k, r = 25, 0.15
        q = partition_points(jnp.asarray(pts), bucket_size=32)
        warm0 = warm_start_self(q, k, max_radius=r)
        st = knn_update_tiled(warm0, q, q, skip_self=jnp.int32(1))
        d = np.asarray(scatter_back(
            extract_final_result(st).reshape(q.num_buckets, q.bucket_size),
            q.pos, len(pts), fill=jnp.inf))
        assert_dist_equal(d, kth_nn_dist(pts, pts, k, max_radius=r))


class TestPointGroup:
    """Coarsened point side (point_group knob): fine query buckets keep the
    prune radius tight while resident tiles stay wide."""

    def test_coarsen_buckets_is_reshape(self):
        from mpi_cuda_largescaleknn_tpu.ops.partition import coarsen_buckets

        pts = random_points(500, seed=51)
        q = partition_points(jnp.asarray(pts), bucket_size=16)
        c = coarsen_buckets(q, 4)
        assert c.num_buckets == q.num_buckets // 4
        assert c.bucket_size == q.bucket_size * 4
        np.testing.assert_array_equal(
            np.asarray(c.pts).reshape(-1, 3), np.asarray(q.pts).reshape(-1, 3))
        np.testing.assert_array_equal(
            np.asarray(c.ids).reshape(-1), np.asarray(q.ids).reshape(-1))
        # union bounds cover every real point of the group
        p = np.asarray(c.pts)
        lo, hi = np.asarray(c.lower), np.asarray(c.upper)
        for b in range(c.num_buckets):
            real = p[b][p[b, :, 0] < PAD_SENTINEL / 2]
            if len(real):
                assert np.all(real >= lo[b] - 1e-6)
                assert np.all(real <= hi[b] + 1e-6)

    def test_unordered_group_matches_group1(self):
        from mpi_cuda_largescaleknn_tpu.core.config import KnnConfig
        from mpi_cuda_largescaleknn_tpu.models.unordered import UnorderedKNN
        from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh

        pts = random_points(900, seed=52)
        base = UnorderedKNN(KnnConfig(k=6, engine="tiled", bucket_size=16),
                            mesh=get_mesh(1)).run(pts)
        grouped = UnorderedKNN(
            KnnConfig(k=6, engine="tiled", bucket_size=16, point_group=4),
            mesh=get_mesh(1)).run(pts)
        np.testing.assert_array_equal(base, grouped)

    def test_unordered_group_pallas_oracle_8dev(self):
        from mpi_cuda_largescaleknn_tpu.core.config import KnnConfig
        from mpi_cuda_largescaleknn_tpu.models.unordered import UnorderedKNN
        from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh

        pts = random_points(800, seed=53)
        model = UnorderedKNN(
            KnnConfig(k=4, engine="pallas_tiled", bucket_size=16,
                      point_group=2), mesh=get_mesh(8))
        got = model.run(pts)
        assert_dist_equal(got, kth_nn_dist(pts, pts, 4))
        assert model.last_stats["pair_evals"] > 0

    def test_demand_group_matches_group1(self):
        from mpi_cuda_largescaleknn_tpu.core.config import KnnConfig
        from mpi_cuda_largescaleknn_tpu.models.prepartitioned import (
            PrePartitionedKNN,
        )
        from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh

        pts = random_points(800, seed=54)
        pts = pts[np.argsort(pts[:, 0], kind="stable")]
        parts = [pts[i * 200:(i + 1) * 200] for i in range(4)]
        base = PrePartitionedKNN(
            KnnConfig(k=5, engine="tiled", bucket_size=16),
            mesh=get_mesh(4)).run(parts)
        grouped = PrePartitionedKNN(
            KnnConfig(k=5, engine="tiled", bucket_size=16, point_group=4),
            mesh=get_mesh(4)).run(parts)
        for b, g in zip(base, grouped):
            np.testing.assert_array_equal(b, g)

    def test_group_clamps_to_bucket_count(self):
        from mpi_cuda_largescaleknn_tpu.core.config import KnnConfig
        from mpi_cuda_largescaleknn_tpu.models.unordered import UnorderedKNN
        from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh

        # tiny input: group far exceeds the bucket count -> clamped, exact
        pts = random_points(60, seed=55)
        got = UnorderedKNN(
            KnnConfig(k=3, engine="tiled", bucket_size=16, point_group=64),
            mesh=get_mesh(1)).run(pts)
        assert_dist_equal(got, kth_nn_dist(pts, pts, 3))
