"""Chunked-query streaming ring (ring_knn_chunked) — the beyond-HBM heap
regime (SURVEY.md §7 hard part #4). Heaps live only for the active chunk;
tree shards stay resident and rotate a full ring per chunk."""

import numpy as np
import pytest

from mpi_cuda_largescaleknn_tpu.core.config import KnnConfig
from mpi_cuda_largescaleknn_tpu.models.unordered import UnorderedKNN
from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
from mpi_cuda_largescaleknn_tpu.parallel.ring import (
    ring_knn,
    ring_knn_chunked,
)
from tests.oracle import assert_dist_equal, kth_nn_dist, random_points
from tests.test_checkpoint import _sharded


@pytest.mark.parametrize("chunk_rows", [16, 23, 64, 100])
def test_chunked_matches_fused(chunk_rows):
    """Any chunk size — even ones that split unevenly — reproduces the
    one-shot ring bit-for-bit."""
    pts = random_points(520, seed=3)
    mesh = get_mesh(8)
    flat, ids, _, _ = _sharded(pts, 8)
    fused = np.asarray(ring_knn(flat, ids, 6, mesh, bucket_size=16))
    chunked = ring_knn_chunked(flat, ids, 6, mesh, chunk_rows=chunk_rows,
                               bucket_size=16)
    np.testing.assert_array_equal(fused, chunked)


@pytest.mark.parametrize("depth", [1, 2, 3])
def test_chunked_pipeline_depths_identical(depth):
    """The host/device pipeline (pre-staged chunk c+1, deferred result
    fetch) must be invisible in the results at every depth — including
    depth 1, the fully serialized loop."""
    pts = random_points(520, seed=17)
    mesh = get_mesh(8)
    flat, ids, _, _ = _sharded(pts, 8)
    fused = np.asarray(ring_knn(flat, ids, 6, mesh, bucket_size=16))
    got = ring_knn_chunked(flat, ids, 6, mesh, chunk_rows=16,
                           bucket_size=16, pipeline_depth=depth)
    np.testing.assert_array_equal(fused, got)


def test_chunked_pipelined_checkpoint_resume(tmp_path):
    """Checkpointing forces a pipeline drain before each snapshot: a
    pipelined run interrupted mid-stream must resume to the exact result."""
    pts = random_points(512, seed=19)
    mesh = get_mesh(8)
    flat, ids, _, _ = _sharded(pts, 8)
    cdir = str(tmp_path / "ck")
    want = ring_knn_chunked(flat, ids, 5, mesh, chunk_rows=16,
                            bucket_size=16, pipeline_depth=3)
    partial = ring_knn_chunked(flat, ids, 5, mesh, chunk_rows=16,
                               bucket_size=16, checkpoint_dir=cdir,
                               max_chunks=2, pipeline_depth=3)
    assert not np.array_equal(partial, want)  # later chunks still inf
    resumed = ring_knn_chunked(flat, ids, 5, mesh, chunk_rows=16,
                               bucket_size=16, checkpoint_dir=cdir,
                               pipeline_depth=3)
    np.testing.assert_array_equal(resumed, want)


def test_chunked_with_candidates():
    pts = random_points(256, seed=5)
    mesh = get_mesh(8)
    flat, ids, _, _ = _sharded(pts, 8)
    _, cands = ring_knn_chunked(flat, ids, 4, mesh, chunk_rows=16,
                                bucket_size=16, return_candidates=True)
    _, want = ring_knn(flat, ids, 4, mesh, bucket_size=16,
                       return_candidates=True)
    np.testing.assert_array_equal(np.asarray(want.dist2), cands.dist2)


def test_chunked_resume(tmp_path):
    """Die after 2 of 4 chunks; relaunch completes only the remaining
    chunks and matches the uninterrupted result."""
    pts = random_points(512, seed=7)
    mesh = get_mesh(8)
    flat, ids, _, _ = _sharded(pts, 8)
    cdir = str(tmp_path / "ck")
    want = ring_knn_chunked(flat, ids, 5, mesh, chunk_rows=16,
                            bucket_size=16)
    partial = ring_knn_chunked(flat, ids, 5, mesh, chunk_rows=16,
                               bucket_size=16, checkpoint_dir=cdir,
                               max_chunks=2)
    assert not np.array_equal(partial, want)  # later chunks still inf
    resumed = ring_knn_chunked(flat, ids, 5, mesh, chunk_rows=16,
                               bucket_size=16, checkpoint_dir=cdir)
    np.testing.assert_array_equal(resumed, want)


def test_model_level_chunked_oracle():
    pts = random_points(430, seed=11)
    k = 7
    cfg = KnnConfig(k=k, bucket_size=16, query_chunk=16)
    got = UnorderedKNN(cfg, mesh=get_mesh(8)).run(pts)
    assert_dist_equal(got, kth_nn_dist(pts, pts, k))


def test_model_level_chunked_neighbors():
    pts = random_points(200, seed=13)
    cfg = KnnConfig(k=3, bucket_size=16, query_chunk=16)
    d, idx = UnorderedKNN(cfg, mesh=get_mesh(8)).run(
        pts, return_neighbors=True)
    full = ((pts[:, None, :] - pts[None, :, :]) ** 2).sum(-1)
    rows = np.arange(200)
    np.testing.assert_allclose(np.sqrt(full[rows, idx[:, -1]]), d, rtol=1e-6)
    assert np.array_equal(idx[:, 0], rows)


def test_chunked_point_group_matches_ungrouped():
    """Chunked drivers coarsen only the resident side: results must be
    byte-identical to the ungrouped chunked run on both pipelines."""
    import numpy as np

    from mpi_cuda_largescaleknn_tpu.core.config import KnnConfig
    from mpi_cuda_largescaleknn_tpu.models.prepartitioned import (
        PrePartitionedKNN,
    )
    from mpi_cuda_largescaleknn_tpu.models.unordered import UnorderedKNN
    from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
    from tests.oracle import random_points

    pts = random_points(600, seed=71)
    base = UnorderedKNN(KnnConfig(k=5, engine="tiled", bucket_size=16,
                                  query_chunk=40), mesh=get_mesh(4)).run(pts)
    grouped = UnorderedKNN(KnnConfig(k=5, engine="tiled", bucket_size=16,
                                     query_chunk=40, point_group=4),
                           mesh=get_mesh(4)).run(pts)
    np.testing.assert_array_equal(base, grouped)

    srt = pts[np.argsort(pts[:, 0], kind="stable")]
    parts = [srt[i * 150:(i + 1) * 150] for i in range(4)]
    base_p = PrePartitionedKNN(KnnConfig(k=5, engine="tiled", bucket_size=16,
                                         query_chunk=40),
                               mesh=get_mesh(4)).run(parts)
    grp_p = PrePartitionedKNN(KnnConfig(k=5, engine="tiled", bucket_size=16,
                                        query_chunk=40, point_group=4),
                              mesh=get_mesh(4)).run(parts)
    for b, g in zip(base_p, grp_p):
        np.testing.assert_array_equal(b, g)
